"""The measurement tooling is round evidence infrastructure — pin its
merge/guard semantics so a regression can't silently destroy measured
results (bench.py `_load_prior`/`headline_summary`, tools/measure_session
merge/retry logic).  Pure-JSON logic, no device needed."""

import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def _ms():
    spec = importlib.util.spec_from_file_location(
        "measure_session", REPO / "tools" / "measure_session.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


PARAMS = {"model": "m", "batch": 8, "prompt_len": 64, "new_tokens": 128,
          "flagship": "f"}


def test_merge_error_never_clobbers_measured():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    art = ms.merge(art, "sweep", {"points": [1]}, PARAMS)
    art = ms.merge(art, "sweep", {"error": "late boom"}, PARAMS)
    assert art["extras"]["sweep"] == {"points": [1]}
    assert "error" in art["extras"]["sweep_rerun"]


def test_merge_retry_attempts_and_exhaustion():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    for n in range(ms.MAX_ATTEMPTS):
        assert not ms.leg_exhausted(art, "sweep")
        art = ms.merge(art, "sweep", {"error": "boom"}, PARAMS)
    assert ms.leg_exhausted(art, "sweep")
    # a success resets the ledger
    art = ms.merge(art, "sweep", {"points": [2]}, PARAMS)
    assert ms.leg_done(art, "sweep") and not ms.leg_exhausted(art, "sweep")


def test_merge_headline_error_never_clobbers_measured():
    ms = _ms()
    art = {"note": "", "metric": "m0", "value": 1.0, "headline": {"x": 1},
           "extras": {}}
    art = ms.merge(art, "headline", {"error": "h"}, PARAMS)
    # the measured top-level value/metric/headline survive the failure
    assert art["value"] == 1.0 and art["metric"] == "m0"
    assert art["headline"] == {"x": 1}
    assert "error" in art["extras"]["headline_rerun"]
    # a measured leg is done: it never re-enters the todo list, so
    # exhaustion bookkeeping is moot for it
    assert ms.leg_done(art, "headline")


def test_merge_unmeasured_headline_errors_exhaust():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    for _ in range(ms.MAX_ATTEMPTS):
        assert not ms.leg_exhausted(art, "headline")
        art = ms.merge(art, "headline", {"error": "h"}, PARAMS)
    assert art["headline"] == {}           # still unmeasured, never faked
    assert ms.leg_exhausted(art, "headline")


def test_load_prior_skips_errors_and_stamps_provenance(tmp_path,
                                                       monkeypatch):
    art = {"note": "n", "metric": "m", "value": 2.0, "vs_baseline": 3.0,
           "headline": {"decode_tokens_per_sec": 2.0},
           "extras": {"good": {"v": 1}, "bad": {"error": "x"},
                      "bad_rerun": {"error": "y"},
                      "baseline": {"tokens_per_sec": 1}}}
    p = tmp_path / "prior.json"
    p.write_text(json.dumps(art))
    monkeypatch.setattr(bench, "REPO", tmp_path)
    monkeypatch.setenv("BENCH_PRIOR_ARTIFACT", "prior.json")
    prior = bench._load_prior()
    assert set(prior["legs"]) == {"headline", "good"}
    assert "prior.json" in prior["source"] and "written" in prior["source"]
    assert prior["value"] == 2.0


def test_load_prior_missing_artifact(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "REPO", tmp_path)
    assert bench._load_prior() == {}


def test_merge_forced_rerun_failures_accumulate_attempts():
    # an errored --force re-run of a MEASURED leg lands in the rerun
    # slot with a running attempts counter (without it, repeatedly
    # failing forced re-runs never registered in the retry ledger)
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    art = ms.merge(art, "sweep", {"points": [1]}, PARAMS)
    art = ms.merge(art, "sweep", {"error": "a"}, PARAMS)
    art = ms.merge(art, "sweep", {"error": "b"}, PARAMS)
    assert art["extras"]["sweep"] == {"points": [1]}   # still measured
    assert art["extras"]["sweep_rerun"]["attempts"] == 2


def test_session_ceiling_is_max_probe_and_labels_suspect_legs():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {
        "roofline_probe": {"hbm_read_gbs": 300.0},
        "probe_history": [{"hbm_gbs": 450.0}, {"hbm_gbs": 120.0}]}}
    assert ms.session_ceiling(art) == 450.0
    # a decode leg beating every probe gets probe_inconsistent and NO
    # measured fraction — a >1.0 "roofline fraction" is an apology
    # masquerading as a measurement (the r05 artifact shipped 1.691)
    art = ms.merge(art, "headline_int8", {"achieved_gbs": 500.0}, PARAMS)
    r = art["extras"]["headline_int8"]
    assert "hbm_roofline_frac_measured" not in r
    assert "probe_inconsistent" in r
    # a later, healthier probe raises the ceiling, the fraction comes
    # back and the inconsistency stamp clears
    art["extras"]["probe_history"].append({"hbm_gbs": 600.0})
    art = ms.merge(art, "pipeline", {"tok_s": 1}, PARAMS)
    r = art["extras"]["headline_int8"]
    assert r["hbm_roofline_frac_measured"] < 1.0
    assert "probe_inconsistent" not in r
    assert art["extras"]["measured_ceiling_gbs"] == 600.0


def test_load_prior_chains_artifacts_with_per_leg_provenance(
        tmp_path, monkeypatch):
    new = {"note": "r5", "metric": "m5", "value": 5.0, "vs_baseline": 1.5,
           "headline": {"decode_tokens_per_sec": 5.0},
           "extras": {"probe_history": [{"hbm_gbs": 1}]}}
    old = {"note": "r4", "metric": "m4", "value": 4.0, "vs_baseline": 1.4,
           "headline": {"decode_tokens_per_sec": 4.0},
           "extras": {"sweep": {"points": [1]}}}
    (tmp_path / "new.json").write_text(json.dumps(new))
    (tmp_path / "old.json").write_text(json.dumps(old))
    monkeypatch.setattr(bench, "REPO", tmp_path)
    monkeypatch.setenv("BENCH_PRIOR_ARTIFACT", "new.json")
    monkeypatch.setattr(bench, "PRIOR_ARTIFACT_FALLBACKS", ["old.json"])
    prior = bench._load_prior()
    # headline from the newest artifact, sweep borrowed from the older
    # one — each stamped with the artifact it came from
    assert prior["value"] == 5.0
    assert "new.json" in prior["legs"]["headline"]["prior_source"]
    assert "old.json" in prior["legs"]["sweep"]["prior_source"]
    # probe_history is session bookkeeping, never surfaced as a leg
    assert "probe_history" not in prior["legs"]


def test_headline_summary_null_when_not_comparable():
    # a different batch than the stored CPU baseline must report null,
    # never a mislabeled multiplier
    s = bench.headline_summary(
        {"decode_tokens_per_sec": 100.0, "dtype": "bf16"},
        dict(PARAMS, model="tinyllama-1.1b", batch=999), "dev")
    assert s["value"] == 100.0 and s["vs_baseline"] is None
