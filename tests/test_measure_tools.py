"""The measurement tooling is round evidence infrastructure — pin its
merge/guard semantics so a regression can't silently destroy measured
results (bench.py `_load_prior`/`headline_summary`, tools/measure_session
merge/retry logic).  Pure-JSON logic, no device needed."""

import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


def _ms():
    spec = importlib.util.spec_from_file_location(
        "measure_session", REPO / "tools" / "measure_session.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


PARAMS = {"model": "m", "batch": 8, "prompt_len": 64, "new_tokens": 128,
          "flagship": "f"}


def test_merge_error_never_clobbers_measured():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    art = ms.merge(art, "sweep", {"points": [1]}, PARAMS)
    art = ms.merge(art, "sweep", {"error": "late boom"}, PARAMS)
    assert art["extras"]["sweep"] == {"points": [1]}
    assert "error" in art["extras"]["sweep_rerun"]


def test_merge_retry_attempts_and_exhaustion():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    for n in range(ms.MAX_ATTEMPTS):
        assert not ms.leg_exhausted(art, "sweep")
        art = ms.merge(art, "sweep", {"error": "boom"}, PARAMS)
    assert ms.leg_exhausted(art, "sweep")
    # a success resets the ledger
    art = ms.merge(art, "sweep", {"points": [2]}, PARAMS)
    assert ms.leg_done(art, "sweep") and not ms.leg_exhausted(art, "sweep")


def test_merge_headline_error_never_clobbers_measured():
    ms = _ms()
    art = {"note": "", "metric": "m0", "value": 1.0, "headline": {"x": 1},
           "extras": {}}
    art = ms.merge(art, "headline", {"error": "h"}, PARAMS)
    # the measured top-level value/metric/headline survive the failure
    assert art["value"] == 1.0 and art["metric"] == "m0"
    assert art["headline"] == {"x": 1}
    assert "error" in art["extras"]["headline_rerun"]
    # a measured leg is done: it never re-enters the todo list, so
    # exhaustion bookkeeping is moot for it
    assert ms.leg_done(art, "headline")


def test_merge_unmeasured_headline_errors_exhaust():
    ms = _ms()
    art = {"note": "", "headline": {}, "extras": {}}
    for _ in range(ms.MAX_ATTEMPTS):
        assert not ms.leg_exhausted(art, "headline")
        art = ms.merge(art, "headline", {"error": "h"}, PARAMS)
    assert art["headline"] == {}           # still unmeasured, never faked
    assert ms.leg_exhausted(art, "headline")


def test_load_prior_skips_errors_and_stamps_provenance(tmp_path,
                                                       monkeypatch):
    art = {"note": "n", "metric": "m", "value": 2.0, "vs_baseline": 3.0,
           "headline": {"decode_tokens_per_sec": 2.0},
           "extras": {"good": {"v": 1}, "bad": {"error": "x"},
                      "bad_rerun": {"error": "y"},
                      "baseline": {"tokens_per_sec": 1}}}
    p = tmp_path / "prior.json"
    p.write_text(json.dumps(art))
    monkeypatch.setattr(bench, "REPO", tmp_path)
    monkeypatch.setenv("BENCH_PRIOR_ARTIFACT", "prior.json")
    prior = bench._load_prior()
    assert set(prior["legs"]) == {"headline", "good"}
    assert "prior.json" in prior["source"] and "written" in prior["source"]
    assert prior["value"] == 2.0


def test_load_prior_missing_artifact(monkeypatch, tmp_path):
    monkeypatch.setattr(bench, "REPO", tmp_path)
    assert bench._load_prior() == {}


def test_headline_summary_null_when_not_comparable():
    # a different batch than the stored CPU baseline must report null,
    # never a mislabeled multiplier
    s = bench.headline_summary(
        {"decode_tokens_per_sec": 100.0, "dtype": "bf16"},
        dict(PARAMS, model="tinyllama-1.1b", batch=999), "dev")
    assert s["value"] == 100.0 and s["vs_baseline"] is None
