"""Integrated root-server app: the full register → profile → plan →
distribute → run → serve composition (VERDICT r1 item 3; reference
``server.py:583-1052``).

The workers are *bare*: they get only the registry address and a device id
— no topology, no layer ranges, and no weights seed.  Stage weights arrive
through the lifecycle artifact channel from the server's parameter set, so
token-level parity with a local engine proves the whole chain.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine

MODEL = "llama-test"
SEED = 123      # distinctive: workers must NOT be able to derive weights
PROMPT = [[5, 17, 42, 7, 99, 3, 12, 56]]


def _cpu_env():
    return dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
                XLA_FLAGS="--xla_force_host_platform_device_count=1")


def _read_until(proc, prefix, timeout=180.0, sink=None):
    """Read stdout lines until one starts with ``prefix``; returns it."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            assert proc.poll() is None, \
                f"process died waiting for {prefix!r} (rc={proc.returncode})"
            time.sleep(0.05)
            continue
        line = line.strip()
        if sink is not None:
            sink.append(line)
        if line.startswith(prefix):
            return line
    raise AssertionError(f"{prefix!r} not seen within {timeout}s "
                         f"(saw {sink})")


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", ["", "float8_e4m3fn"])
def test_server_with_bare_workers_end_to_end(tmp_path, kv_dtype):
    """The composed server e2e; the fp8 variant proves --kv-cache-dtype
    rides the OPEN RunConfig to every auto worker's stage cache (greedy
    parity vs a ref engine with the SAME cache dtype) AND runs the HTTP
    surface through the dynamic-batching backend (--pool-size 2:
    generate + stats + classify all ride the scheduler thread)."""
    cfg = get_model_config(MODEL)
    ref_engine = InferenceEngine(
        cfg, init_full_params(jax.random.PRNGKey(SEED), cfg),
        max_seq=64, sampling=SamplingParams(greedy=True),
        kv_cache_dtype=kv_dtype or None)
    want = ref_engine.generate(np.asarray(PROMPT, np.int32), 8).tokens

    env = _cpu_env()
    server = subprocess.Popen(
        [sys.executable, "-m", "distributed_inference_demo_tpu", "server",
         "--model", MODEL, "--num-workers", "2", "--max-seq", "64",
         "--max-new-tokens", "8", "--greedy", "--weights-seed", str(SEED),
         "--collect-timeout", "300", "--monitor-timeout", "300",
         "--step-timeout", "300"]
        + (["--kv-cache-dtype", kv_dtype, "--pool-size", "2"]
           if kv_dtype else []),
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True)
    workers = []
    log = []
    try:
        registry = _read_until(server, "SERVER_REGISTRY", sink=log).split()[1]
        for wid in ("w1", "w2"):
            workers.append(subprocess.Popen(
                [sys.executable, "-m", "distributed_inference_demo_tpu",
                 "worker", "--auto", "--registry", registry,
                 "--device-id", wid, "--step-timeout", "300"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=env, text=True))

        plan_line = _read_until(server, "SERVER_PLAN", timeout=300, sink=log)
        ranges = json.loads(plan_line.split(" ", 1)[1])
        assert set(ranges) == {"header", "w1", "w2"}
        covered = sorted(tuple(r) for r in ranges.values())
        assert covered[0][0] == 0 and covered[-1][1] == cfg.num_layers

        http = _read_until(server, "HTTP_READY", timeout=300,
                           sink=log).split()[1]

        body = json.dumps({"prompt_ids": PROMPT,
                           "max_new_tokens": 8}).encode()
        req = urllib.request.Request(
            http + "/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as r:
            tokens = np.asarray(json.loads(r.read())["tokens"])
        np.testing.assert_array_equal(tokens, want)

        # hot-loop stats flow across all three stages
        with urllib.request.urlopen(http + "/stats", timeout=60) as r:
            stats = json.loads(r.read())
        assert len(stats["stages"]) == 3
        assert {s["role"] for s in stats["stages"]} == \
            {"header", "worker", "tail"}

        # classification rides the same composed pipeline (task_type
        # "classification" implemented end to end, VERDICT r2 item 7):
        # bare workers speak the c:/ctok: protocol natively
        labels = [7, 42, 99]
        want_cls = ref_engine.classify(np.asarray(PROMPT, np.int32), labels)
        body = json.dumps({"prompt_ids": PROMPT,
                           "label_token_ids": labels}).encode()
        req = urllib.request.Request(
            http + "/classify", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as r:
            got_cls = json.loads(r.read())["labels"]
        assert got_cls == want_cls.tolist()
    finally:
        server.kill()
        for w in workers:
            w.kill()
