"""Transport tests: tagged delivery, stashing, timeouts — loopback and ZMQ."""

import threading

import pytest

from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport, TransportError, TransportTimeout,
    ZmqTransport)


def make_loopback_pair():
    net = LoopbackNetwork()
    return LoopbackTransport("a", net), LoopbackTransport("b", net)


def make_zmq_pair():
    a = ZmqTransport("a")
    b = ZmqTransport("b")
    a.connect("b", b.address)
    b.connect("a", a.address)
    return a, b


@pytest.fixture(params=["loopback", "zmq"])
def pair(request):
    a, b = make_loopback_pair() if request.param == "loopback" \
        else make_zmq_pair()
    yield a, b
    a.close()
    b.close()


def test_send_recv_tagged(pair):
    a, b = pair
    a.send("b", "h:0:0", b"payload0")
    a.send("b", "h:0:1", b"payload1")
    assert b.recv("h:0:0", timeout=5) == b"payload0"
    assert b.recv("h:0:1", timeout=5) == b"payload1"


def test_recv_stashes_other_tags(pair):
    a, b = pair
    a.send("b", "h:1:0", b"later")
    a.send("b", "h:0:0", b"wanted")
    # ask for the second message first: the first must be stashed, not lost
    assert b.recv("h:0:0", timeout=5) == b"wanted"
    assert b.recv("h:1:0", timeout=5) == b"later"


def test_recv_any_drains_stash_first(pair):
    a, b = pair
    a.send("b", "x", b"1")
    a.send("b", "y", b"2")
    assert b.recv("y", timeout=5) == b"2"      # stashes "x"
    tag, payload = b.recv_any(timeout=5)
    assert (tag, payload) == ("x", b"1")


def test_recv_timeout(pair):
    _, b = pair
    with pytest.raises(TransportTimeout):
        b.recv("nope", timeout=0.1)
    with pytest.raises(TransportTimeout):
        b.recv_any(timeout=0.1)


def test_bidirectional(pair):
    a, b = pair
    a.send("b", "ping", b"x")
    assert b.recv("ping", timeout=5) == b"x"
    b.send("a", "pong", b"y")
    assert a.recv("pong", timeout=5) == b"y"


def test_send_unknown_peer_raises():
    t = ZmqTransport("solo")
    try:
        with pytest.raises(TransportError, match="not connected"):
            t.send("ghost", "t", b"")
    finally:
        t.close()


def test_concurrent_senders(pair):
    a, b = pair
    n = 50

    def sender(tag_prefix):
        for i in range(n):
            a.send("b", f"{tag_prefix}:{i}", str(i).encode())

    threads = [threading.Thread(target=sender, args=(p,))
               for p in ("t0", "t1")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for p in ("t0", "t1"):
        for i in range(n):
            assert b.recv(f"{p}:{i}", timeout=5) == str(i).encode()
