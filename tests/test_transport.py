"""Transport tests: tagged delivery, stashing, timeouts — loopback and ZMQ."""

import threading

import pytest

from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport, TransportError, TransportTimeout,
    ZmqTransport)


def make_loopback_pair():
    net = LoopbackNetwork()
    return LoopbackTransport("a", net), LoopbackTransport("b", net)


def make_zmq_pair():
    a = ZmqTransport("a")
    b = ZmqTransport("b")
    a.connect("b", b.address)
    b.connect("a", a.address)
    return a, b


@pytest.fixture(params=["loopback", "zmq"])
def pair(request):
    a, b = make_loopback_pair() if request.param == "loopback" \
        else make_zmq_pair()
    yield a, b
    a.close()
    b.close()


def test_send_recv_tagged(pair):
    a, b = pair
    a.send("b", "h:0:0", b"payload0")
    a.send("b", "h:0:1", b"payload1")
    assert b.recv("h:0:0", timeout=5) == b"payload0"
    assert b.recv("h:0:1", timeout=5) == b"payload1"


def test_recv_stashes_other_tags(pair):
    a, b = pair
    a.send("b", "h:1:0", b"later")
    a.send("b", "h:0:0", b"wanted")
    # ask for the second message first: the first must be stashed, not lost
    assert b.recv("h:0:0", timeout=5) == b"wanted"
    assert b.recv("h:1:0", timeout=5) == b"later"


def test_recv_any_drains_stash_first(pair):
    a, b = pair
    a.send("b", "x", b"1")
    a.send("b", "y", b"2")
    assert b.recv("y", timeout=5) == b"2"      # stashes "x"
    tag, payload = b.recv_any(timeout=5)
    assert (tag, payload) == ("x", b"1")


def test_recv_timeout(pair):
    _, b = pair
    with pytest.raises(TransportTimeout):
        b.recv("nope", timeout=0.1)
    with pytest.raises(TransportTimeout):
        b.recv_any(timeout=0.1)


def test_bidirectional(pair):
    a, b = pair
    a.send("b", "ping", b"x")
    assert b.recv("ping", timeout=5) == b"x"
    b.send("a", "pong", b"y")
    assert a.recv("pong", timeout=5) == b"y"


def test_send_unknown_peer_raises():
    t = ZmqTransport("solo")
    try:
        with pytest.raises(TransportError, match="not connected"):
            t.send("ghost", "t", b"")
    finally:
        t.close()


def _counter_value(c, **labels) -> float:
    want = tuple(sorted(labels.items()))
    for _name, lab, value in c.samples():
        if tuple(sorted(lab)) == want:
            return value
    return 0.0


def test_send_retries_with_backoff_then_timeout():
    """A blocked peer (full HWM, nobody reading) exhausts the bounded
    retries — each retry counted — then surfaces as TransportTimeout,
    never a hang."""
    from distributed_inference_demo_tpu.telemetry import catalog
    a = ZmqTransport("ra", hwm=1, send_timeout=0.05, send_retries=2,
                     retry_backoff=0.01)
    b = ZmqTransport("rb", hwm=1)
    b._stop.set()                  # stop rb's pump: nobody drains the queue
    b._thread.join(timeout=5)
    a.connect("rb", b.address)
    before = _counter_value(catalog.TRANSPORT_SEND_RETRIES)
    try:
        with pytest.raises(TransportTimeout, match="blocked"):
            for i in range(64):    # HWM 1 + TCP buffers: fill until Again
                a.send("rb", "t", b"x" * 65536)
        # >=: the terminal send burns its full retry budget (2); earlier
        # sends may each count transient backpressure retries too
        assert _counter_value(catalog.TRANSPORT_SEND_RETRIES) >= before + 2
    finally:
        a.close()
        b.close()


def test_reconnect_rebuilds_socket_and_counts():
    from distributed_inference_demo_tpu.telemetry import catalog
    a, b = make_zmq_pair()
    try:
        a.send("b", "t1", b"before")
        assert b.recv("t1", timeout=5) == b"before"
        before = _counter_value(catalog.TRANSPORT_RECONNECTS)
        a._reconnect("b")
        assert _counter_value(catalog.TRANSPORT_RECONNECTS) == before + 1
        a.send("b", "t2", b"after")     # the fresh socket works
        assert b.recv("t2", timeout=5) == b"after"
    finally:
        a.close()
        b.close()


def test_send_retry_duplicates_are_receiver_safe(pair):
    """The retry contract: re-sending the same (tag, payload) is safe
    because ring receivers dedup by (rid, step) — at the transport level
    both copies arrive; the dedup lives above (test_chaos pins it)."""
    a, b = pair
    a.send("b", "h:0:0", b"p")
    a.send("b", "h:0:0", b"p")        # what a retry after a lost ack does
    assert b.recv("h:0:0", timeout=5) == b"p"
    assert b.recv("h:0:0", timeout=5) == b"p"


def test_concurrent_senders(pair):
    a, b = pair
    n = 50

    def sender(tag_prefix):
        for i in range(n):
            a.send("b", f"{tag_prefix}:{i}", str(i).encode())

    threads = [threading.Thread(target=sender, args=(p,))
               for p in ("t0", "t1")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for p in ("t0", "t1"):
        for i in range(n):
            assert b.recv(f"{p}:{i}", timeout=5) == str(i).encode()
