"""CLI + HTTP endpoint tests.

The reference's HTTP endpoint answers every inference request with
"Inference not implemented yet" (``server.py:671-678``); ours must actually
infer — including streaming — and the CLI must cover the serve / worker /
plan / generate / bench roles (SURVEY.md §7.9).
"""

import json
import http.client
import io
import threading
from contextlib import redirect_stdout

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu import cli
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)
from distributed_inference_demo_tpu.runtime.http_server import (
    InferenceHTTPServer)

GREEDY = SamplingParams(greedy=True)


@pytest.fixture(scope="module")
def http_server():
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    server = InferenceHTTPServer(engine, port=0, model_name="llama-test")
    server.start()
    yield server, engine
    server.shutdown()


def _req(server, method, path, body=None):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=60)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_health(http_server):
    server, _ = http_server
    status, data = _req(server, "GET", "/health")
    assert status == 200
    body = json.loads(data)
    assert body["status"] == "ok" and body["model"] == "llama-test"


def test_generate_endpoint_matches_engine(http_server):
    server, engine = http_server
    prompt = [[5, 17, 42, 7]]
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "max_new_tokens": 6})
    assert status == 200
    got = json.loads(data)["tokens"]
    want = engine.generate(np.asarray(prompt), 6).tokens.tolist()
    assert got == want


def test_generate_endpoint_logprobs(http_server):
    server, engine = http_server
    prompt = [[5, 17, 42, 7]]
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "max_new_tokens": 5,
                         "logprobs": True})
    assert status == 200
    body = json.loads(data)
    assert len(body["logprobs"][0]) == 5
    assert all(lp <= 0 for lp in body["logprobs"][0])
    want = engine.generate(np.asarray(prompt), 5,
                           logprobs=True).logprobs[0]
    np.testing.assert_allclose(body["logprobs"][0], want, atol=1e-5)


def test_generate_endpoint_logprobs_unsupported_backend():
    """Backends without a logprobs parameter get a clean 501, not a 500."""
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)

    class NoLogprobs:
        max_seq = 64

        def generate(self, prompt_ids, max_new_tokens, seed=0):
            raise AssertionError("must not be called")

    server = InferenceHTTPServer(NoLogprobs(), port=0)
    server.start()
    try:
        status, data = _req(server, "POST", "/generate",
                            {"prompt_ids": [[1]], "max_new_tokens": 2,
                             "logprobs": True})
        assert status == 501
        assert "logprobs" in json.loads(data)["error"]
    finally:
        server.shutdown()


def test_generate_endpoint_stream_logprobs(http_server):
    """Streaming with logprobs: each JSONL line carries the step's token
    logprobs, matching the blocking path's values."""
    server, engine = http_server
    prompt = [[5, 17, 42, 7]]
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "max_new_tokens": 4,
                         "stream": True, "logprobs": True})
    assert status == 200
    lines = [json.loads(l) for l in data.decode().splitlines() if l.strip()]
    assert len(lines) == 4
    want = engine.generate(np.asarray(prompt), 4, logprobs=True).logprobs[0]
    got = np.asarray([l["logprobs"][0] for l in lines])
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_generate_endpoint_stream_logprobs_unsupported_backend():
    """Stream backends without logprobs support still get a clean 501."""
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)

    class NoLogprobsStream:
        max_seq = 64

        def generate_stream(self, prompt_ids, max_new_tokens, seed=0):
            raise AssertionError("must not be called")

    server = InferenceHTTPServer(NoLogprobsStream(), port=0)
    server.start()
    try:
        status, data = _req(server, "POST", "/generate",
                            {"prompt_ids": [[1]], "max_new_tokens": 2,
                             "stream": True, "logprobs": True})
        assert status == 501
        assert "logprobs" in json.loads(data)["error"]
    finally:
        server.shutdown()


def test_generate_endpoint_streaming(http_server):
    server, engine = http_server
    prompt = [[5, 17, 42, 7]]
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "max_new_tokens": 6,
                         "stream": True})
    assert status == 200
    lines = [json.loads(l) for l in data.decode().strip().splitlines()]
    assert [l["step"] for l in lines] == list(range(6))
    got = [[l["tokens"][0] for l in lines]]
    want = engine.generate(np.asarray(prompt), 6).tokens.tolist()
    assert got == want


def test_stream_capacity_error_is_clean_400(http_server):
    """A capacity error on a stream request must be a clean 400 —
    surfaced from the generator's first step BEFORE the 200 + chunked
    headers are committed (a late error would splice a status line into
    the open chunked body)."""
    server, _ = http_server
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": [[1, 2, 3]], "max_new_tokens": 1000,
                         "stream": True})
    assert status == 400 and b"error" in data


def test_generate_endpoint_bad_requests(http_server):
    server, _ = http_server
    status, data = _req(server, "POST", "/generate", {"max_new_tokens": 4})
    assert status == 400 and b"prompt" in data
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": [[1, 2]], "max_new_tokens": 1000})
    assert status == 400 and b"capacity" in data.lower() or status == 400
    status, _ = _req(server, "GET", "/nope")
    assert status == 404


def _run_cli(argv):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(argv)
    return rc, buf.getvalue()


def test_cli_generate_greedy():
    rc, out = _run_cli([
        "generate", "--model", "llama-test", "--prompt-ids", "5,17,42,7",
        "--max-new-tokens", "4", "--greedy", "--max-seq", "64",
        "--attn-backend", "jnp"])
    assert rc == 0
    body = json.loads(out)
    assert len(body["tokens"][0]) == 4

    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    want = engine.generate(np.asarray([[5, 17, 42, 7]]), 4).tokens.tolist()
    assert body["tokens"] == want


# slow lane: CLI twin of the engine-level self-draft pins in
# test_speculative; the generate surface stays quick via the greedy test
@pytest.mark.slow
def test_cli_generate_speculative_self_draft():
    """generate --draft-model with draft == target (same seed-init) must
    reproduce plain greedy output exactly with 100% acceptance."""
    argv_tail = ["--model", "llama-test", "--prompt-ids", "5,17,42,7",
                 "--max-new-tokens", "6", "--greedy", "--max-seq", "64",
                 "--attn-backend", "jnp"]
    rc, plain = _run_cli(["generate"] + argv_tail)
    assert rc == 0
    rc, spec = _run_cli(["generate"] + argv_tail +
                        ["--draft-model", "llama-test", "--num-draft", "3"])
    assert rc == 0
    plain, spec = json.loads(plain), json.loads(spec)
    assert spec["tokens"] == plain["tokens"]
    assert spec["speculative"]["acceptance_rate"] == 1.0
    assert spec["speculative"]["tokens_per_round"] > 1.0


@pytest.mark.slow
def test_cli_generate_prompt_lookup():
    """--prompt-lookup greedy must match plain greedy; exclusive with
    --draft-model."""
    argv_tail = ["--model", "llama-test", "--prompt-ids", "5,17,42,7",
                 "--max-new-tokens", "8", "--greedy", "--max-seq", "64",
                 "--attn-backend", "jnp"]
    rc, plain = _run_cli(["generate"] + argv_tail)
    assert rc == 0
    rc, pld = _run_cli(["generate"] + argv_tail + ["--prompt-lookup"])
    assert rc == 0
    plain, pld = json.loads(plain), json.loads(pld)
    assert pld["tokens"] == plain["tokens"]
    assert "speculative" in pld
    rc, _ = _run_cli(["generate"] + argv_tail +
                     ["--prompt-lookup", "--draft-model", "llama-test"])
    assert rc == 1


@pytest.mark.slow
def test_cli_generate_tp():
    """generate --tp 2 on the virtual mesh matches single-device greedy;
    --tp combined with another serve mode is rejected."""
    argv_tail = ["--model", "llama-test", "--prompt-ids", "5,17,42,7",
                 "--max-new-tokens", "6", "--greedy", "--max-seq", "64",
                 "--attn-backend", "jnp"]
    rc, plain = _run_cli(["generate"] + argv_tail)
    assert rc == 0
    rc, tp = _run_cli(["generate"] + argv_tail[:-2] + ["--tp", "2"])
    assert rc == 0
    assert json.loads(tp)["tokens"] == json.loads(plain)["tokens"]
    # --tp composes with speculation modes too
    rc, tp_pld = _run_cli(["generate"] + argv_tail[:-2] +
                          ["--tp", "2", "--prompt-lookup"])
    assert rc == 0
    assert json.loads(tp_pld)["tokens"] == json.loads(plain)["tokens"]


def test_cli_plan_and_cache(tmp_path):
    devices = [
        {"device_id": "cpu0", "address": "127.0.0.1:7000",
         "flops_per_sec": 1e11, "platform": "cpu"},
        {"device_id": "tpu0", "address": "127.0.0.1:7001",
         "flops_per_sec": 2e14, "platform": "tpu", "chips": 4},
    ]
    dev_file = tmp_path / "devices.json"
    dev_file.write_text(json.dumps(devices))
    plan_file = tmp_path / "plan.json"

    rc, out = _run_cli(["plan", "--model", "llama-test",
                        "--devices", str(dev_file),
                        "--save", str(plan_file)])
    assert rc == 0
    plan = json.loads(out)
    ranges = [tuple(s["layers"]) for s in plan["stages"]]
    assert ranges[0][0] == 0 and ranges[-1][1] == 4
    # the TPU device (2000x the FLOPs) must get at least as many layers
    n0 = ranges[0][1] - ranges[0][0]
    n1 = ranges[1][1] - ranges[1][0]
    assert n1 >= n0
    assert plan_file.exists()

    rc, out = _run_cli(["plan", "--model", "llama-test",
                        "--load", str(plan_file)])
    assert rc == 0
    assert json.loads(out) == plan


def test_chat_repl_streams_incrementally(http_server, monkeypatch):
    """The chat REPL (L7: the reference's ChatScreen loop as a terminal
    app) must render tokens chunk by chunk — incremental arrivals, ending
    with the exact greedy tokens the engine produces."""
    import time as _time

    server, engine = http_server
    prompt = [[5, 17, 42, 7]]
    want = engine.generate(np.asarray(prompt), 6).tokens

    # stream_generate yields one parsed line per arrived chunk
    arrivals = []
    lines = []
    for item in cli.stream_generate(server.host, server.port,
                                    {"prompt_ids": prompt,
                                     "max_new_tokens": 6}):
        arrivals.append(_time.perf_counter())
        lines.append(item)
    assert [l["step"] for l in lines] == list(range(6))
    assert [l["tokens"][0] for l in lines] == want[0].tolist()
    assert arrivals[0] < arrivals[-1]   # first chunk before completion

    # full REPL e2e: two turns then /quit, token ids rendered in order
    monkeypatch.setattr(cli.sys, "stdin",
                        io.StringIO("5,17,42,7\n5,17,42,7\n/quit\n"))
    rc, out = _run_cli(["chat", "--url",
                        f"http://{server.host}:{server.port}",
                        "--max-new-tokens", "6", "--ids"])
    assert rc == 0
    rendered = " ".join(str(t) for t in want[0].tolist())
    assert out.count(rendered) == 2


def test_load_full_params_honors_checkpoint(tmp_path):
    """ADVICE r1 #1: the serve --chain path must load --checkpoint weights,
    not silently seed-init.  Both serve branches go through
    _load_full_params; assert it returns the checkpointed tree, which is
    distinguishable from every seed-init."""
    import argparse

    from distributed_inference_demo_tpu.checkpoint import save_params

    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(123), cfg)
    # perturb so the tree cannot equal ANY seed-init
    params.embed["tokens"] = params.embed["tokens"] + 1.5
    ckpt = str(tmp_path / "ckpt")
    save_params(ckpt, params, cfg, model_name="llama-test")

    args = argparse.Namespace(model="llama-test", checkpoint=ckpt,
                              weights_seed=0)
    loaded = cli._load_full_params(args, cfg)
    np.testing.assert_allclose(np.asarray(loaded.embed["tokens"]),
                               np.asarray(params.embed["tokens"]))

    args_no = argparse.Namespace(model="llama-test", checkpoint=None,
                                 weights_seed=0)
    seeded = cli._load_full_params(args_no, cfg)
    assert not np.allclose(np.asarray(seeded.embed["tokens"]),
                           np.asarray(loaded.embed["tokens"]))


def test_cli_bench_runs():
    rc, out = _run_cli([
        "bench", "--model", "llama-test", "--batch", "2",
        "--prompt-len", "8", "--max-new-tokens", "4", "--max-seq", "32",
        "--attn-backend", "jnp"])
    assert rc == 0
    body = json.loads(out)
    assert body["unit"] == "tokens/sec" and body["value"] > 0


@pytest.mark.slow
def test_cli_bench_prompt_lookup():
    """bench --prompt-lookup reports baseline + speculative tok/s with
    acceptance stats on one workload."""
    rc, out = _run_cli([
        "bench", "--model", "llama-test", "--batch", "2",
        "--prompt-len", "8", "--max-new-tokens", "8", "--greedy",
        "--max-seq", "64", "--attn-backend", "jnp", "--prompt-lookup",
        "--num-draft", "3"])
    assert rc == 0
    body = json.loads(out)
    assert body["value"] > 0
    spec = body["speculative"]
    assert spec["tokens_per_sec"] > 0 and spec["speedup"] > 0
    assert spec["rounds"] >= 1


def test_serve_mode_pairing_rules(capsys):
    """--batch-slots composes with --draft-model; every other mode pair
    stays an explicit one-line error."""
    base = ["serve", "--model", "llama-test"]
    assert cli.main(base + ["--chain", "w@127.0.0.1:1",
                            "--batch-slots", "2"]) == 1
    assert cli.main(base + ["--draft-model", "llama-test",
                            "--prompt-lookup"]) == 1
    assert cli.main(base + ["--chain", "w@127.0.0.1:1",
                            "--prompt-lookup"]) == 1
    # --no-spec-adaptive pins K_row in the mixed slot loop; outside
    # serve --batch-slots + a proposer it would silently do nothing
    assert cli.main(base + ["--no-spec-adaptive"]) == 1
    assert cli.main(base + ["--batch-slots", "2",
                            "--no-spec-adaptive"]) == 1
    assert cli.main(["generate", "--model", "llama-test",
                     "--prompt-ids", "1,2", "--no-spec-adaptive"]) == 1
    capsys.readouterr()


@pytest.mark.slow
def test_http_batching_with_draft(http_server):
    """The composed serving shape (continuous batching x speculative
    decoding) over HTTP: greedy output matches the plain engine, /stats
    reports acceptance."""
    _, engine = http_server
    backend = ContinuousBatchingEngine(
        engine.cfg, engine.params, max_seq=64, max_batch=2,
        sampling=GREEDY, prompt_buckets=(16,), draft_cfg=engine.cfg,
        draft_params=engine.params, num_draft=3)
    server = InferenceHTTPServer(backend, port=0, model_name="llama-test")
    server.start()
    try:
        prompt = [[5, 17, 42, 7]]
        status, data = _req(server, "POST", "/generate",
                            {"prompt_ids": prompt, "max_new_tokens": 6})
        assert status == 200
        want = engine.generate(np.asarray(prompt), 6).tokens.tolist()
        assert json.loads(data)["tokens"] == want
        status, stats = _req(server, "GET", "/stats")
        assert status == 200
        assert json.loads(stats)["speculative"]["acceptance_rate"] == 1.0
    finally:
        server.shutdown()
        backend.close()


@pytest.mark.slow
def test_cli_generate_sp_matches_plain():
    """generate --sp 2 (ring AND ulysses) on the virtual mesh must equal
    plain greedy decode; non-divisible prompts and mode mixing are
    rejected with one-line errors."""
    ids = ",".join(str(i % 250) for i in range(16))
    argv = ["generate", "--model", "llama-test", "--prompt-ids", ids,
            "--max-new-tokens", "6", "--greedy", "--max-seq", "32"]
    rc, plain = _run_cli(argv + ["--attn-backend", "jnp"])
    assert rc == 0
    for strategy in ("ring", "ulysses"):
        rc, out = _run_cli(argv + ["--sp", "2", "--sp-strategy", strategy])
        assert rc == 0
        assert json.loads(out)["tokens"] == json.loads(plain)["tokens"]
    # --kv-cache-dtype composes with --sp: parity vs the plain engine
    # with the SAME reduced cache dtype (attention reads what the cache
    # stores on both sides)
    rc, plain_fp8 = _run_cli(argv + ["--kv-cache-dtype", "float8_e4m3fn"])
    assert rc == 0
    rc, out = _run_cli(argv + ["--sp", "2",
                               "--kv-cache-dtype", "float8_e4m3fn"])
    assert rc == 0
    assert json.loads(out)["tokens"] == json.loads(plain_fp8)["tokens"]
    # flags the sp paths have no plumbing for are rejected loudly
    for extra in (["--eos-id", "7"], ["--attn-backend", "jnp"]):
        rc, _ = _run_cli(argv + ["--sp", "2"] + extra)
        assert rc == 1
    # 15 tokens don't shard over sp=2
    bad = ",".join(str(i % 250) for i in range(15))
    rc, _ = _run_cli(["generate", "--model", "llama-test", "--prompt-ids",
                      bad, "--max-new-tokens", "4", "--greedy",
                      "--max-seq", "32", "--sp", "2"])
    assert rc == 1
    rc, _ = _run_cli(argv + ["--sp", "2", "--prompt-lookup"])
    assert rc == 1


# slow lane: HTTP twin of the engine-level pld parity pins in
# test_batching; the HTTP batching surface stays quick elsewhere
@pytest.mark.slow
def test_http_batching_with_prompt_lookup(http_server):
    """Continuous batching x draft-free speculation over HTTP: greedy
    output matches the plain engine, /stats names the proposer."""
    _, engine = http_server
    backend = ContinuousBatchingEngine(
        engine.cfg, engine.params, max_seq=64, max_batch=2,
        sampling=GREEDY, prompt_buckets=(16,), prompt_lookup=True,
        num_draft=3)
    server = InferenceHTTPServer(backend, port=0, model_name="llama-test")
    server.start()
    try:
        prompt = [[5, 17, 42, 7]]
        status, data = _req(server, "POST", "/generate",
                            {"prompt_ids": prompt, "max_new_tokens": 6})
        assert status == 200
        want = engine.generate(np.asarray(prompt), 6).tokens.tolist()
        assert json.loads(data)["tokens"] == want
        status, stats = _req(server, "GET", "/stats")
        assert json.loads(stats)["speculative"]["proposer"] == \
            "prompt_lookup"
    finally:
        server.shutdown()
        backend.close()


def test_chat_streaming_detok_holds_back_split_utf8(monkeypatch):
    """Incremental detokenization: a multi-byte UTF-8 char split across
    two tokens renders once, complete — never as replacement chars."""
    import io
    from contextlib import redirect_stdout

    class FakeTok:
        def encode(self, text):
            return [1]

        def decode(self, ids, skip_special=True):
            frag = {1: b"a", 2: b"\xc3", 3: b"\xa9"}   # 2+3 = "é"
            return b"".join(frag[int(i)] for i in ids).decode(
                "utf-8", errors="replace")

    def fake_stream(host, port, payload):
        yield {"step": 0, "tokens": [2]}
        yield {"step": 1, "tokens": [3]}

    monkeypatch.setattr(cli, "_load_tokenizer", lambda p: FakeTok())
    monkeypatch.setattr(cli, "stream_generate", fake_stream)
    monkeypatch.setattr(cli.sys, "stdin", io.StringIO("hi\n/quit\n"))
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["chat", "--url", "http://127.0.0.1:1",
                       "--tokenizer", "fake"])
    assert rc == 0
    out = buf.getvalue()
    assert "é" in out and "�" not in out


def test_stop_matcher_fuzz():
    """StopMatcher vs a whole-string reference over random texts, stop
    sets, and chunkings — INCLUDING per-token (1-char) feeds: identical
    cut positions regardless of chunking (the chunk-dependent-cut bug:
    a short stop completing while an earlier-starting longer stop is
    still a live prefix must defer, ADVICE r5), and emitted text never
    contains anything later retracted (the streaming holdback
    guarantee)."""
    import random

    from distributed_inference_demo_tpu.runtime.http_server import (
        StopMatcher)

    rng = random.Random(7)
    for _ in range(300):
        text = "".join(rng.choice("abc") for _ in range(rng.randint(0, 40)))
        stops = ["".join(rng.choice("abc")
                         for _ in range(rng.randint(1, 4)))
                 for _ in range(rng.randint(1, 3))]
        hits = [text.find(s) for s in stops if s in text]
        ref_pos = min(hits) if hits else None

        # every chunking — per-char, random, whole-string — must agree
        # with the whole-string reference on (pos, emitted)
        chunkings = [1, None, len(text) or 1]
        for chunk in chunkings:
            m = StopMatcher(stops)
            outs, matched = [], False
            i = 0
            while i < len(text) and not matched:
                j = i + (chunk if chunk else rng.randint(1, 5))
                out, matched = m.feed(text[i:j])
                outs.append(out)
                i = j
            if not matched:
                # stream over: resolve any deferred verdict
                out, matched = m.finish()
                outs.append(out)
            if ref_pos is None:
                assert not matched and m.pos is None
                assert "".join(outs) == text
            else:
                assert matched and m.pos == ref_pos, (text, stops, chunk)
                assert "".join(outs) == text[:ref_pos]


def test_stop_matcher_defers_short_stop_inside_longer_candidate():
    """The ADVICE r5 repro pinned: stop=["abc", "b"] fed "a" then "b"
    must NOT cut at 1 while "ab" can still become "abc" — the verdict
    defers (bounded by the longest stop) and resolves identically to
    whole-string feeding whichever way the tail goes."""
    from distributed_inference_demo_tpu.runtime.http_server import (
        StopMatcher)

    # tail completes the longer stop: cut at 0, like feeding "abc" whole
    m = StopMatcher(["abc", "b"])
    assert m.feed("a") == ("", False)
    out, matched = m.feed("b")
    assert not matched and out == ""      # deferred, nothing emitted
    out, matched = m.feed("c")
    assert matched and m.pos == 0 and out == ""

    # tail kills the longer candidate: the short stop's cut stands
    m = StopMatcher(["abc", "b"])
    m.feed("a")
    m.feed("b")
    out, matched = m.feed("x")
    assert matched and m.pos == 1 and out == "a"

    # stream ends while deferred: finish() resolves to the short stop
    m = StopMatcher(["abc", "b"])
    m.feed("a")
    m.feed("b")
    out, matched = m.finish()
    assert matched and m.pos == 1 and out == "a"


def test_cli_kvcache_flags():
    """--kv-cache-blocks plumbs into generate, defers to DWT_KVCACHE_*
    env knobs when unset, and is REJECTED (not silently ignored) by
    modes with no block-cache plumbing."""
    argv = ["generate", "--model", "llama-test", "--prompt-ids",
            ",".join(str(i) for i in range(20)), "--max-new-tokens", "4",
            "--greedy", "--max-seq", "64", "--attn-backend", "jnp"]
    rc, plain = _run_cli(argv)
    assert rc == 0
    rc, cached = _run_cli(argv + ["--kv-cache-blocks", "16",
                                  "--kv-block-tokens", "4"])
    assert rc == 0
    # single cold run: the cache changes nothing about the output
    assert json.loads(cached)["tokens"] == json.loads(plain)["tokens"]
    # the prompt-lookup engine gained block-cache plumbing with the
    # universal-paged refactor (docs/DESIGN.md §14): the flags compose
    rc, pld_out = _run_cli(argv + ["--kv-cache-blocks", "16",
                                   "--kv-block-tokens", "4",
                                   "--prompt-lookup"])
    assert rc == 0 and "tokens" in json.loads(pld_out)
    # stage workers still reject the flags loudly (activations have no
    # prompt key to match blocks by — a layout question, not this one)
    rc, _ = _run_cli(["worker", "--model", "llama-test", "--stage-id",
                      "0", "--num-stages", "1", "--layer-start", "0",
                      "--layer-end", "1", "--device-id", "w0", "--port",
                      "1", "--header", "h@127.0.0.1:1",
                      "--kv-cache-blocks", "8"])
    assert rc == 1


def test_cli_serve_batching_kvcache_env_default(monkeypatch):
    """DWT_KVCACHE_BLOCKS steers the batching engine when the flag is
    absent (env knob parity with --kv-cache-blocks)."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    monkeypatch.setenv("DWT_KVCACHE_BLOCKS", "5")
    monkeypatch.setenv("DWT_KVCACHE_BLOCK_TOKENS", "4")
    with ContinuousBatchingEngine(cfg, params, max_seq=64, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        assert eng.kv_cache is not None
        assert eng.kv_cache.num_blocks == 5
        assert eng.kv_cache.block_tokens == 4
    monkeypatch.setenv("DWT_KVCACHE_BLOCKS", "0")
    with ContinuousBatchingEngine(cfg, params, max_seq=64, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        # 0 = the dense-equivalent default pool (the paged-native
        # scheduler has no cache-off mode: the pool IS the decode cache)
        assert (eng.kv_cache.num_blocks
                == eng.max_batch * eng._table_width)


def test_stop_matcher_empty_stop_list_passes_through():
    """An empty stop set is a valid no-op matcher (pure pass-through),
    not a construction error."""
    from distributed_inference_demo_tpu.runtime.http_server import (
        StopMatcher)
    m = StopMatcher([])
    assert m.feed("hello") == ("hello", False)
    out, matched = m.finish()
    assert out == "" and not matched and m.pos is None
