"""Disaggregated prefill/decode with KV page migration (DESIGN.md §15).

The ISSUE-8 invariants, pinned:

- a request served through prefill-worker → page-migration →
  decode-worker join produces a greedy stream BIT-IDENTICAL to the
  colocated engines (the migrated pages hold exactly the K/V the
  decode engine's own cold prefill would write);
- the decode-side join is an ownership ADOPTION: zero page leaks on
  both pools (idle ``used_blocks == tree.block_count``), and
  ``dwt_kvcache_h2d_bytes_total`` stays 0 on the decode side (the
  adopt is a device scatter + block-table reference, never a
  dense-row host gather);
- migration frames are idempotent under duplication (the (rid,
  attempt, seq) dedup) and stale attempts are discarded;
- both roles surface migration state on their debug surfaces;
- ``--kv-layout dense`` fails loudly naming its removal (the escape
  hatch was deprecation-staged here and deleted in the gateway PR).

The chaos-side invariants (faulted migration, prefill crash
rescheduling) live in tests/test_chaos.py.
"""

import threading
import time

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.comm import wire
from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)
from distributed_inference_demo_tpu.runtime.disagg import (
    DecodeWorker, DisaggCoordinator, PrefillWorker, _meta_frame,
    _page_frame, _parse_meta_frame)

GREEDY = SamplingParams(greedy=True)
MODEL = "llama-test"


@pytest.fixture(scope="module")
def cfg_params():
    cfg = get_model_config(MODEL)
    return cfg, init_full_params(jax.random.PRNGKey(0), cfg)


@pytest.fixture(scope="module")
def fabric(cfg_params):
    """One loopback disagg deployment shared by the e2e tests: a
    coordinator, one prefill worker, one decode worker (2 slots)."""
    cfg, params = cfg_params
    net = LoopbackNetwork()
    tc = LoopbackTransport("coord", net)
    tp = LoopbackTransport("p0", net)
    td = LoopbackTransport("d0", net)
    engine = ContinuousBatchingEngine(
        cfg, params, max_seq=64, max_batch=2, sampling=GREEDY,
        kv_cache_blocks=0)
    pw = PrefillWorker(cfg, params, tp, max_seq=64, prefill_chunk=8)
    dw = DecodeWorker(engine, td)
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in (pw, dw)]
    for t in threads:
        t.start()
    coord = DisaggCoordinator(tc, ["p0"], "d0")
    yield coord, pw, dw, engine
    pw.stop()
    dw.stop()
    coord.close()
    engine.close()


@pytest.fixture(scope="module")
def reference(cfg_params):
    cfg, params = cfg_params
    eng = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)

    def run(prompt, max_new):
        return eng.generate(prompt[None], max_new).tokens[0]
    return run


def _assert_no_pool_leaks(pw, engine):
    """Idle ownership invariant on BOTH pools: every allocated page is
    tree-owned (request pages freed at completion, adopted pages
    transferred) — bounded wait for the async completions."""
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        d = engine.kv_cache.snapshot()
        p = pw.kv_cache.snapshot()
        if (d["blocks_used"] == d["tree_blocks"]
                and p["blocks_used"] == p["tree_blocks"]):
            return
        time.sleep(0.05)
    raise AssertionError(
        f"page leak: decode {d['blocks_used']}/{d['tree_blocks']}, "
        f"prefill {p['blocks_used']}/{p['tree_blocks']}")


# ---------------------------------------------------------------------------
# frame codec + dedup units


def test_migration_frame_roundtrip_with_trace():
    k = np.arange(2 * 3 * 2 * 4 * 5, dtype=np.float32).reshape(
        2, 3, 2, 4, 5)
    v = -k
    body = _page_frame(k, v, first_block=7, trace=(0xABCD, 42))
    meta, tensors, ctx = _parse_meta_frame(body)
    assert meta == {"first_block": 7, "n_blocks": 2}
    np.testing.assert_array_equal(tensors[0], k)
    np.testing.assert_array_equal(tensors[1], v)
    assert ctx == (0xABCD, 42)
    # CRC: a flipped byte is detected, never decoded
    bad = bytearray(body)
    bad[len(bad) // 2] ^= 0x40
    with pytest.raises(wire.WireError):
        _parse_meta_frame(bytes(bad))


def test_decode_worker_dedups_and_discards_stale_attempts(cfg_params):
    """(rid, attempt, seq) dedup: a duplicated page frame is dropped
    (idempotent retries), a reorder hole is dropped (go-back-n
    refills), and a newer attempt supersedes the staged older one."""
    cfg, params = cfg_params

    class _FakeEngine:
        def submit_premigrated(self, *a, **k):
            raise AssertionError("no join expected in this test")

    net = LoopbackNetwork()
    td = LoopbackTransport("dx", net)
    LoopbackTransport("px", net)
    dw = DecodeWorker(_FakeEngine(), td)
    blk = np.zeros((1, cfg.num_layers, cfg.num_kv_heads, 16,
                    cfg.head_dim), np.float32)
    f0 = _page_frame(blk, blk, 0)
    assert dw.handle_message("pg:r9:0:0", f0)
    assert dw._staged["r9"]["expected"] == 1
    dw.handle_message("pg:r9:0:0", f0)          # duplicate: dropped
    assert dw._staged["r9"]["expected"] == 1
    dw.handle_message("pg:r9:0:3", f0)          # hole: dropped
    assert dw._staged["r9"]["expected"] == 1
    assert dw.stats["dropped_frames"] == 2
    # a NEWER attempt supersedes the staged one...
    dw.handle_message("pg:r9:1:0", f0)
    assert dw._staged["r9"]["attempt"] == 1
    assert dw._staged["r9"]["expected"] == 1
    # ...and the stale attempt's late frames are discarded
    dw.handle_message("pg:r9:0:1", f0)
    assert dw._staged["r9"]["attempt"] == 1
    assert dw.stats["dropped_frames"] == 3


# ---------------------------------------------------------------------------
# the loopback e2e (the -m quick disagg rep)


@pytest.mark.quick
def test_disagg_loopback_bit_identical_and_leak_free(reference, fabric):
    """THE tentpole scenario at test scale: prefill worker → per-chunk
    page migration → decode-side adopt + join, greedy output
    bit-identical to the colocated reference, zero page leaks on both
    pools, zero decode-side H2D for the migrated pages."""
    coord, pw, dw, engine = fabric
    prompt = (np.arange(37) % 50 + 3).astype(np.int32)
    want = reference(prompt, 8)
    req = coord.submit(prompt, 8)
    got = req.wait(timeout=120)
    np.testing.assert_array_equal(got, want)
    assert req.ttft_s is not None and req.ttft_s > 0
    assert pw.stats["migrated_pages"] >= 2
    assert dw.stats["adopted_pages"] == pw.stats["migrated_pages"]
    assert engine.kv_cache.snapshot()["h2d_bytes"] == 0
    assert engine.disagg_stats["premigrated_requests"] >= 1
    _assert_no_pool_leaks(pw, engine)


def test_disagg_repeat_prompt_migrates_from_prefill_cache(reference,
                                                          fabric):
    """A repeat prompt hits the prefill worker's radix tree: the pages
    migrate straight out of its pool (zero recompute) and the output
    stays bit-identical."""
    coord, pw, dw, engine = fabric
    prompt = (np.arange(41) % 61 + 2).astype(np.int32)
    want = reference(prompt, 6)
    hits_before = pw.kv_cache.stats["hits"]
    np.testing.assert_array_equal(
        coord.submit(prompt, 6).wait(timeout=120), want)
    np.testing.assert_array_equal(
        coord.submit(prompt, 6).wait(timeout=120), want)
    assert pw.kv_cache.stats["hits"] > hits_before
    _assert_no_pool_leaks(pw, engine)


def test_disagg_short_prompt_degrades_to_plain_submit(reference,
                                                      fabric):
    """A prompt with no migratable whole block (len <= block_tokens)
    ships zero pages and joins as an ordinary cold admission."""
    coord, pw, dw, engine = fabric
    prompt = np.asarray([7, 9, 11], np.int32)
    want = reference(prompt, 6)
    np.testing.assert_array_equal(
        coord.submit(prompt, 6).wait(timeout=120), want)
    _assert_no_pool_leaks(pw, engine)


def test_disagg_join_rejection_fails_request_not_worker(reference,
                                                        fabric):
    """A decode-side admission rejection (here: the capacity bound) is
    a per-REQUEST failure surfaced through fin — the decode worker's
    serve loop survives and keeps joining later migrations."""
    coord, pw, dw, engine = fabric
    prompt = (np.arange(37) % 50 + 3).astype(np.int32)
    req = coord.submit(prompt, 60)       # 37 + 60 > max_seq 64
    with pytest.raises(RuntimeError, match="exceeds KV-cache capacity"):
        req.wait(timeout=120)
    # the worker is alive: a well-sized request still serves
    want = reference(prompt, 4)
    np.testing.assert_array_equal(
        coord.submit(prompt, 4).wait(timeout=120), want)
    _assert_no_pool_leaks(pw, engine)


def test_disagg_debug_surfaces_migration_state(fabric):
    """The /debugz satellite: all three roles name their migration
    state — in-flight handoffs, staged/adopted pages, last migration
    latency — so a wedged handoff is observable from a scrape."""
    coord, pw, dw, engine = fabric
    p = pw.debug_state()
    assert p["role"] == "prefill"
    assert "inflight_handoff" in p and "handoff_backlog" in p
    assert p["migration"]["migrated_pages"] >= 1
    assert p["migration"]["last_migration_ms"] is not None
    assert p["kvcache"]["layout"] == "paged"
    d = dw.debug_state()
    assert d["role"] == "decode"
    assert d["staged_migrations"] == {}        # nothing mid-flight
    assert d["migration"]["adopted_pages"] >= 1
    assert d["migration"]["last_migration_ms"] is not None
    assert "kvcache" in d["engine"]
    c = coord.debug_state()
    assert c["role"] == "coordinator"
    assert c["handoff_queue_depth"] == 0
    assert c["alive_prefill_workers"] == ["p0"]


# ---------------------------------------------------------------------------
# the engine join seam


def test_submit_premigrated_validates_block_shapes(cfg_params, fabric):
    cfg, _ = cfg_params
    eng = fabric[3]        # rides the shared engine: validation raises
    bt = eng.kv_cache.block_tokens       # before anything is scheduled
    prompt = np.arange(2 * bt + 1, dtype=np.int32) + 2
    good = np.zeros((2, cfg.num_layers, cfg.num_kv_heads, bt,
                     cfg.head_dim), np.float32)
    with pytest.raises(ValueError, match="n, L, H, bt, D"):
        eng.submit_premigrated(prompt, 4, good[:, :, :, :-1],
                               good[:, :, :, :-1])
    with pytest.raises(ValueError, match="exceed the prompt"):
        eng.submit_premigrated(prompt[:bt], 4, good, good)
    # None blocks = plain submit (short-prompt degenerate)
    req = eng.submit_premigrated(prompt, 2, None, None)
    assert req.wait(timeout=120).shape == (2,)


@pytest.mark.slow
def test_submit_premigrated_matches_cold_engine(cfg_params):
    """The join seam in isolation: blocks exported from a prefill
    worker's row land via submit_premigrated and the stream matches a
    cold colocated run; the adopted pages are tree-owned afterwards.
    Slow lane: redundant-coverage twin of the loopback e2e bit-identity
    (which drives the same seam through the full migration path) — in
    the full lane it only re-buys ~6 s of engine builds."""
    cfg, params = cfg_params
    net = LoopbackNetwork()
    tp = LoopbackTransport("pp", net)
    pw = PrefillWorker(cfg, params, tp, max_seq=64, prefill_chunk=8)
    prompt = (np.arange(33) % 43 + 2).astype(np.int32)
    with ContinuousBatchingEngine(cfg, params, max_seq=64, max_batch=1,
                                  sampling=GREEDY,
                                  kv_cache_blocks=0) as eng:
        bt = eng.kv_cache.block_tokens
        want = eng.submit(prompt, 6).wait(timeout=120)
    # export via the worker's own seam (chunk prefill + block slices)
    import jax.numpy as jnp
    from distributed_inference_demo_tpu.models.base import KVCache
    n_mig = (len(prompt) - 1) // bt
    row = KVCache.create(cfg, cfg.num_layers, 1, 64)
    cache = KVCache(row.keys, row.values, jnp.int32(0))
    pos = 0
    while pos < n_mig * bt:
        step = min(8, n_mig * bt - pos)
        chunk = np.zeros((1, 8), np.int32)
        chunk[0, :step] = prompt[pos:pos + step]
        cache = pw._chunk_mid(pw.params, jnp.asarray(chunk), cache,
                              jnp.int32(pos))
        pos += step
    k, v = pw._export_blocks(cache.keys, cache.values, 0, n_mig)
    with ContinuousBatchingEngine(cfg, params, max_seq=64, max_batch=1,
                                  sampling=GREEDY,
                                  kv_cache_blocks=0) as eng2:
        req = eng2.submit_premigrated(prompt, 6, k, v)
        np.testing.assert_array_equal(req.wait(timeout=120), want)
        assert eng2.disagg_stats == {"premigrated_requests": 1,
                                     "adopted_pages": n_mig}
        snap = eng2.kv_cache.snapshot()
        assert snap["h2d_bytes"] == 0
        assert snap["blocks_used"] == snap["tree_blocks"]


# ---------------------------------------------------------------------------
# CLI role split + dense deprecation satellites


def test_worker_cli_stage_role_requires_stage_args(capsys):
    from distributed_inference_demo_tpu.runtime import worker_main
    rc = worker_main.main(["--model", MODEL, "--device-id", "w",
                           "--port", "0"])
    assert rc == 1
    err = capsys.readouterr().err
    assert "--role stage requires" in err and "--header" in err


def test_worker_cli_stage_role_still_rejects_kv_cache_flags(capsys):
    from distributed_inference_demo_tpu.runtime import worker_main
    rc = worker_main.main([
        "--model", MODEL, "--stage-id", "1", "--num-stages", "2",
        "--layer-start", "0", "--layer-end", "2", "--device-id", "w",
        "--port", "0", "--header", "h@127.0.0.1:1",
        "--kv-cache-blocks", "8"])
    assert rc == 1
    assert "not supported" in capsys.readouterr().err


def test_dense_layout_removed_fails_loudly():
    """ROADMAP item 1 tail, final stage: the dense escape hatch
    (deprecation-staged in this PR's predecessor) is DELETED —
    resolving to 'dense' (flag, env, or kwarg: one owner) raises a
    ValueError naming the removal and the migration, and the
    once-per-process module-global warning latch is gone with it."""
    import distributed_inference_demo_tpu.runtime.kvcache as kvc
    with pytest.raises(ValueError) as ei:
        kvc.resolve_kv_layout("dense")
    msg = str(ei.value)
    assert "REMOVED" in msg and "paged" in msg
    # the deprecation scaffolding is deleted, not just unused
    assert not hasattr(kvc, "_dense_deprecation_warned")
    assert not hasattr(kvc, "DENSE_REMOVAL_RELEASE")
    assert kvc.KV_LAYOUTS == ("paged",)
    # paged resolves clean
    assert kvc.resolve_kv_layout(None) == "paged"
