"""The universal-paged KV contract (docs/DESIGN.md §14).

Paged is the DEFAULT layout everywhere; dense survives as the explicit
escape hatch on the single-request engines.  The oracle is bit-identity:
the layout is a memory architecture, never a semantics change — so for
every engine in the matrix, paged-vs-dense output (greedy AND sampled,
cold AND radix-primed) must match token for token, and after every
request the page-leak invariant holds (``used == tree.block_count``
with zero live leases: pages are tree-owned or free, nothing dangles).

The paged-primed coverage for the batching scheduler, chunked prefill,
``stream_block`` fusion, and the speculative slot modes lives in
tests/test_paged_batching.py, tests/test_kvcache.py (which exercise the
default = paged backend), and tests/test_device_loop.py; this file pins
what those do not: the dense escape hatch's parity, sampled-path
parity, the tp-mesh and ring-stage paged paths, and the speculative
page-sharing ownership story.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import StageSpec
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import (InferenceEngine,
                                                    SpeculativeEngine)
from distributed_inference_demo_tpu.runtime.prompt_lookup import (
    PromptLookupEngine)

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)
SAMPLED = SamplingParams(temperature=0.7, top_k=7)
POOL = dict(kv_cache_blocks=32, kv_block_tokens=4)
SHARED = list(range(2, 22))                  # 20 tokens = 5 blocks
PROMPT = np.asarray([SHARED + [51, 52, 53]])


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


def assert_drained(backend):
    """Paged leak invariant: every page is tree-owned or free, and no
    lease pin outlives its request."""
    mgr = backend.mgr
    assert mgr.used_blocks == mgr.tree.block_count
    assert backend.debug_state()["leased_nodes"] == 0


def both_layouts(make):
    """(dense_result, paged_result) for cold + primed runs of one
    engine recipe; asserts the paged backend drains and moved zero
    bytes through the host."""
    outs = []
    for layout in ("dense", "paged"):
        eng = make(layout)
        prime = np.asarray([SHARED + [90]])
        run = (lambda p: eng.generate(p, 8)) if not isinstance(
            eng, tuple) else None
        cold = eng.generate(PROMPT, 8)
        eng.generate(prime, 4)               # prime the radix tree
        primed = eng.generate(PROMPT, 8)
        snap = eng.kv_cache.snapshot()
        assert snap["hits"] >= 1, layout
        if layout == "paged":
            assert snap["h2d_bytes"] == 0
            assert_drained(eng.kv_cache)
        else:
            assert snap["h2d_bytes"] > 0     # the dense cost paged deletes
        outs.append((cold, primed))
    return outs


_GREEDY_REF = []


def greedy_reference(params):
    """The plain-engine greedy token reference, built at most once per
    process (an engine build costs seconds; several parity tests pin
    against the same stream)."""
    if not _GREEDY_REF:
        _GREEDY_REF.append(InferenceEngine(
            CFG, params, max_seq=96, sampling=GREEDY,
            **POOL).generate(PROMPT, 8).tokens)
    return _GREEDY_REF[0]


@pytest.mark.quick
def test_plain_engine_paged_vs_dense_greedy(params):
    """InferenceEngine: the dense escape hatch and the paged default
    agree bit-for-bit — greedy, cold and radix-primed (the tier-1
    layout-parity oracle; the sampled + fused-streaming matrix rides
    the slow lane now that dense is deprecation-staged)."""
    (d_cold, d_primed), (p_cold, p_primed) = both_layouts(
        lambda layout: InferenceEngine(
            CFG, params, max_seq=96, sampling=GREEDY,
            kv_layout=layout, **POOL))
    np.testing.assert_array_equal(d_cold.tokens, p_cold.tokens)
    np.testing.assert_array_equal(d_primed.tokens, p_primed.tokens)
    np.testing.assert_array_equal(d_cold.tokens, d_primed.tokens)


@pytest.mark.slow
def test_plain_engine_paged_vs_dense_sampled_and_fused(params):
    """The rest of the plain-engine layout matrix: SAMPLED parity and
    fused streaming (stream_block > 1) over a primed paged pool.  Slow
    lane: the greedy oracle above pins the shared code path in tier-1,
    and dense is deprecation-staged (§14) — the full matrix re-buys
    ~7 s per run."""
    (d_cold, d_primed), (p_cold, p_primed) = both_layouts(
        lambda layout: InferenceEngine(
            CFG, params, max_seq=96, sampling=SAMPLED,
            kv_layout=layout, **POOL))
    np.testing.assert_array_equal(d_cold.tokens, p_cold.tokens)
    np.testing.assert_array_equal(d_primed.tokens, p_primed.tokens)
    np.testing.assert_array_equal(d_cold.tokens, d_primed.tokens)
    greedy_tokens = greedy_reference(params)
    # the device loop's K-token blocks ride the seeded-suffix path too
    fused = InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY,
                            stream_block=4, **POOL)
    fused.generate(np.asarray([SHARED + [90]]), 4)       # prime
    streamed = np.concatenate(list(fused.generate_stream(PROMPT, 8)))
    np.testing.assert_array_equal(streamed, greedy_tokens[0])
    assert fused.kv_cache.stats["hits"] >= 1
    assert_drained(fused.kv_cache)


def _pld_layout_parity(params, sampling):
    results = {}
    for layout in ("dense", "paged"):
        eng = PromptLookupEngine(CFG, params, max_seq=96,
                                 sampling=sampling, num_draft=3,
                                 kv_layout=layout, **POOL)
        cold, _ = eng.generate(PROMPT, 8)
        eng.generate(np.asarray([SHARED + [90]]), 4)
        primed, _ = eng.generate(PROMPT, 8)
        np.testing.assert_array_equal(cold.tokens, primed.tokens)
        assert eng.kv_cache.stats["hits"] >= 1
        if layout == "paged":
            assert eng.kv_cache.snapshot()["h2d_bytes"] == 0
            assert_drained(eng.kv_cache)
        results[layout] = cold.tokens
    np.testing.assert_array_equal(results["dense"], results["paged"])


@pytest.mark.slow
def test_prompt_lookup_engine_paged_vs_dense(params):
    """PromptLookupEngine (NEW kv-cache consumer): both layouts, cold
    and primed, greedy parity; paged drains.  Slow lane since dense
    went deprecation-staged (§14): the paged half of this path is
    pinned in tier-1 by test_prompt_lookup.py, and the greedy plain-
    engine oracle covers the dense backend."""
    _pld_layout_parity(params, GREEDY)


@pytest.mark.slow
def test_prompt_lookup_engine_paged_vs_dense_sampled(params):
    _pld_layout_parity(params, SAMPLED)


def test_speculative_page_sharing_ownership(params):
    """Speculative target prefills SHARE prefix pages: the second
    request sharing a prompt prefix adds no new pages for it (the radix
    tree declines duplicates and the request references the same pages
    in HBM), h2d stays 0, and completion drains to tree-only
    ownership."""
    cfg8 = get_model_config("llama-test-int8")
    params8 = init_full_params(jax.random.PRNGKey(0), cfg8,
                               quantize=True)
    spec = SpeculativeEngine(CFG, params, cfg8, params8, max_seq=96,
                             sampling=GREEDY, num_draft=3, **POOL)
    assert spec.kv_layout == "paged"
    r1, _ = spec.generate(PROMPT, 8)
    snap1 = spec.kv_cache.snapshot()
    r2, _ = spec.generate(PROMPT, 8)
    snap2 = spec.kv_cache.snapshot()
    np.testing.assert_array_equal(r1.tokens, r2.tokens)
    # no duplicate pages for the accepted prefix: the re-run stored
    # nothing new and the pool grew by zero blocks
    assert snap2["stored_blocks"] == snap1["stored_blocks"]
    assert snap2["blocks_used"] == snap1["blocks_used"]
    assert snap2["hits"] >= 1 and snap2["h2d_bytes"] == 0
    assert_drained(spec.kv_cache)
    # dense escape hatch agrees token for token
    dense = SpeculativeEngine(CFG, params, cfg8, params8, max_seq=96,
                              sampling=GREEDY, num_draft=3,
                              kv_layout="dense", **POOL)
    rd, _ = dense.generate(PROMPT, 8)
    np.testing.assert_array_equal(rd.tokens, r1.tokens)


@pytest.mark.slow
def test_tp_mesh_engine_paged_vs_dense(params, devices):
    """tp-mesh path: the paged backend's pool composes with the
    kv-head-sharded working cache — greedy parity across layouts on a
    2-chip mesh, primed path included.  Slow lane since dense went
    deprecation-staged (§14); tp×paged composition stays covered in
    tier-1 by test_paged_batching's mesh tests."""
    from distributed_inference_demo_tpu.parallel import (MeshConfig,
                                                         make_mesh)
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)
    mesh = make_mesh(MeshConfig(tp=2), devices[:2])
    sharded = shard_engine_params(params, CFG, mesh)
    toks = {}
    for layout in ("dense", "paged"):
        eng = InferenceEngine(CFG, sharded, max_seq=96, sampling=GREEDY,
                              mesh=mesh, kv_layout=layout, **POOL)
        cold = eng.generate(PROMPT, 8)
        primed = eng.generate(PROMPT, 8)     # full-prompt radix hit
        np.testing.assert_array_equal(cold.tokens, primed.tokens)
        assert eng.kv_cache.stats["hits"] >= 1
        if layout == "paged":
            assert_drained(eng.kv_cache)
        toks[layout] = cold.tokens
    np.testing.assert_array_equal(toks["dense"], toks["paged"])


@pytest.mark.quick
def test_ring_stage_runtime_paged_vs_dense(params):
    """The ring-stage path: a loopback single-stage StageRuntime decodes
    the same greedy tokens on the paged per-stage pool as on dense
    per-rid rows (prefill chunk + fused-tail steps), and ``free(rid)``
    returns every page to the pool."""
    from distributed_inference_demo_tpu.runtime.distributed import (
        StageRuntime)
    spec = StageSpec(0, 1, 0, CFG.num_layers)
    prompt = PROMPT.astype(np.int32)
    toks = {}
    for layout in ("dense", "paged"):
        rt = StageRuntime(CFG, spec, params, max_seq=64,
                          sampling=GREEDY, kv_layout=layout)
        out = []
        tok = rt.run_chunk_sample(7, 0, prompt)
        out.append(tok.copy())
        for step in range(1, 6):
            tok = rt.run_chunk_sample(7, step, tok[:, None])
            out.append(tok.copy())
        toks[layout] = np.stack(out, axis=1)
        if layout == "paged":
            held = sum(1 for v in rt._tables[7].flat
                       if v != rt._sentinel)
            assert held == -(-int(rt._rid_len[7]) // rt._bt)
            free_before = len(rt._pool_free)
            rt.free(7)
            assert len(rt._pool_free) == free_before + held
            assert not rt._tables
    np.testing.assert_array_equal(toks["dense"], toks["paged"])


def test_sp_backend_accepts_both_layouts(params):
    """The sp backend accepts the universal layout flag and surfaces it
    on /stats (its cache is per-request sequence-sharded scratch either
    way — documented in runtime/sp_backend.py)."""
    from distributed_inference_demo_tpu.parallel.mesh import local_sp_mesh
    from distributed_inference_demo_tpu.runtime.sp_backend import (
        SequenceParallelBackend)
    mesh = local_sp_mesh(2)
    be = SequenceParallelBackend(CFG, params, mesh, max_seq=64)
    assert be.stats()["kv_layout"] == "paged"
    be2 = SequenceParallelBackend(CFG, params, mesh, max_seq=64,
                                  kv_layout="dense")
    assert be2.stats()["kv_layout"] == "dense"
