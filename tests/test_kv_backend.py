"""The universal-paged KV contract (docs/DESIGN.md §14).

Paged is the ONLY layout — the dense escape hatch and its backend were
deleted (the gateway release), which retired the dense-parity twin
matrix this file used to run.  What survives is everything the twins
actually proved about the paged path, now pinned directly:

- determinism: cold vs radix-primed runs agree bit-for-bit (a prefix
  hit is a memory optimization, never a semantics change) — greedy in
  tier-1, sampled + fused streaming on the slow lane;
- the zero-copy claim: ``h2d_bytes == 0`` after primed runs (hits are
  device gathers, never host round-trips);
- the page-leak invariant after every request: ``used ==
  tree.block_count`` with zero live leases (pages are tree-owned or
  free, nothing dangles);
- speculative page-sharing ownership (two requests sharing a prefix
  reference the SAME pages in HBM);
- the ring-stage per-stage pool frees every page on ``free(rid)``;
- the sp backend surfaces the universal layout and the removed dense
  layout fails loudly naming the removal.

The paged-primed coverage for the batching scheduler, chunked prefill,
``stream_block`` fusion, and the speculative slot modes lives in
tests/test_paged_batching.py, tests/test_kvcache.py, and
tests/test_device_loop.py.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import StageSpec
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import (InferenceEngine,
                                                    SpeculativeEngine)

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)
SAMPLED = SamplingParams(temperature=0.7, top_k=7)
POOL = dict(kv_cache_blocks=32, kv_block_tokens=4)
SHARED = list(range(2, 22))                  # 20 tokens = 5 blocks
PROMPT = np.asarray([SHARED + [51, 52, 53]])


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


def assert_drained(backend):
    """Paged leak invariant: every page is tree-owned or free, and no
    lease pin outlives its request."""
    mgr = backend.mgr
    assert mgr.used_blocks == mgr.tree.block_count
    assert backend.debug_state()["leased_nodes"] == 0


def cold_and_primed(eng):
    """(cold, primed) results for one engine; asserts the primed run
    hit the radix tree, moved zero bytes through the host, and the
    pool drained."""
    prime = np.asarray([SHARED + [90]])
    cold = eng.generate(PROMPT, 8)
    eng.generate(prime, 4)                   # prime the radix tree
    primed = eng.generate(PROMPT, 8)
    snap = eng.kv_cache.snapshot()
    assert snap["hits"] >= 1
    assert snap["h2d_bytes"] == 0
    assert_drained(eng.kv_cache)
    return cold, primed


_GREEDY_REF = []


def greedy_reference(params):
    """The plain-engine greedy token reference, built at most once per
    process (an engine build costs seconds; several tests pin against
    the same stream)."""
    if not _GREEDY_REF:
        _GREEDY_REF.append(InferenceEngine(
            CFG, params, max_seq=96, sampling=GREEDY,
            **POOL).generate(PROMPT, 8).tokens)
    return _GREEDY_REF[0]


# tier-1 budget: tests/test_kvcache.py::test_engine_primed_vs_cold_
# exactness[8] keeps the quick-lane cold/primed rep on this seam
@pytest.mark.slow
def test_plain_engine_paged_cold_primed_greedy(params):
    """InferenceEngine: a radix-primed greedy run agrees bit-for-bit
    with the cold run and with the shared reference (the tier-1
    prefix-hit oracle; sampled + fused streaming ride the slow
    lane)."""
    cold, primed = cold_and_primed(InferenceEngine(
        CFG, params, max_seq=96, sampling=GREEDY, **POOL))
    np.testing.assert_array_equal(cold.tokens, primed.tokens)
    np.testing.assert_array_equal(cold.tokens, greedy_reference(params))


@pytest.mark.slow
def test_plain_engine_paged_sampled_and_fused(params):
    """The rest of the plain-engine matrix: seeded SAMPLED runs stay
    deterministic across a prefix hit, and fused streaming
    (stream_block > 1) over a primed pool matches the greedy
    reference."""
    cold, primed = cold_and_primed(InferenceEngine(
        CFG, params, max_seq=96, sampling=SAMPLED, **POOL))
    np.testing.assert_array_equal(cold.tokens, primed.tokens)
    # the device loop's K-token blocks ride the seeded-suffix path too
    fused = InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY,
                            stream_block=4, **POOL)
    fused.generate(np.asarray([SHARED + [90]]), 4)       # prime
    streamed = np.concatenate(list(fused.generate_stream(PROMPT, 8)))
    np.testing.assert_array_equal(streamed, greedy_reference(params)[0])
    assert fused.kv_cache.stats["hits"] >= 1
    assert_drained(fused.kv_cache)


# tier-1 budget: the mixed-dispatch spec tests assert draft-pool
# ownership (used==0 idle) every run and are the quick-lane reps
@pytest.mark.slow
def test_speculative_page_sharing_ownership(params):
    """Speculative target prefills SHARE prefix pages: the second
    request sharing a prompt prefix adds no new pages for it (the radix
    tree declines duplicates and the request references the same pages
    in HBM), h2d stays 0, and completion drains to tree-only
    ownership."""
    cfg8 = get_model_config("llama-test-int8")
    params8 = init_full_params(jax.random.PRNGKey(0), cfg8,
                               quantize=True)
    spec = SpeculativeEngine(CFG, params, cfg8, params8, max_seq=96,
                             sampling=GREEDY, num_draft=3, **POOL)
    assert spec.kv_layout == "paged"
    r1, _ = spec.generate(PROMPT, 8)
    snap1 = spec.kv_cache.snapshot()
    r2, _ = spec.generate(PROMPT, 8)
    snap2 = spec.kv_cache.snapshot()
    np.testing.assert_array_equal(r1.tokens, r2.tokens)
    # no duplicate pages for the accepted prefix: the re-run stored
    # nothing new and the pool grew by zero blocks
    assert snap2["stored_blocks"] == snap1["stored_blocks"]
    assert snap2["blocks_used"] == snap1["blocks_used"]
    assert snap2["hits"] >= 1 and snap2["h2d_bytes"] == 0
    assert_drained(spec.kv_cache)


@pytest.mark.quick
def test_ring_stage_runtime_paged(params):
    """The ring-stage path: a loopback single-stage StageRuntime
    decodes the same greedy tokens for two rids sharing one prompt
    (prefill chunk + fused-tail steps are deterministic over the
    per-stage page pool), and ``free(rid)`` returns every page."""
    from distributed_inference_demo_tpu.runtime.distributed import (
        StageRuntime)
    spec = StageSpec(0, 1, 0, CFG.num_layers)
    prompt = PROMPT.astype(np.int32)
    rt = StageRuntime(CFG, spec, params, max_seq=64, sampling=GREEDY)
    toks = {}
    for rid in (7, 8):
        out = []
        tok = rt.run_chunk_sample(rid, 0, prompt)
        out.append(tok.copy())
        for step in range(1, 6):
            tok = rt.run_chunk_sample(rid, step, tok[:, None])
            out.append(tok.copy())
        toks[rid] = np.stack(out, axis=1)
    np.testing.assert_array_equal(toks[7], toks[8])
    held = sum(1 for v in rt._tables[7].flat if v != rt._sentinel)
    assert held == -(-int(rt._rid_len[7]) // rt._bt)
    free_before = len(rt._pool_free)
    rt.free(7)
    assert len(rt._pool_free) == free_before + held
    rt.free(8)
    assert not rt._tables


def test_sp_backend_paged_only(params):
    """The sp backend accepts the universal layout flag, surfaces it on
    /stats, and fails the removed dense layout loudly (its cache is
    per-request sequence-sharded scratch — documented in
    runtime/sp_backend.py)."""
    from distributed_inference_demo_tpu.parallel.mesh import local_sp_mesh
    from distributed_inference_demo_tpu.runtime.sp_backend import (
        SequenceParallelBackend)
    mesh = local_sp_mesh(2)
    be = SequenceParallelBackend(CFG, params, mesh, max_seq=64)
    assert be.stats()["kv_layout"] == "paged"
    with pytest.raises(ValueError, match="REMOVED"):
        SequenceParallelBackend(CFG, params, mesh, max_seq=64,
                                kv_layout="dense")
