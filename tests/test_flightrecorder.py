"""Flight recorder: bounded ring semantics, process default, and the
``dwt_flight_*`` catalog bridge."""

import threading

import pytest

from distributed_inference_demo_tpu.telemetry.flightrecorder import (
    FlightRecorder, get_flight_recorder, set_flight_recorder)


@pytest.fixture(autouse=True)
def _isolate_process_recorder():
    set_flight_recorder(None)
    yield
    set_flight_recorder(None)


@pytest.mark.quick
def test_ring_bounded_keeps_newest():
    fr = FlightRecorder(max_events=4)
    for i in range(10):
        fr.record("x", i=i)
    assert len(fr) == 4
    assert [e["i"] for e in fr.snapshot()] == [6, 7, 8, 9]
    assert fr.total == 10                 # monotone across overwrites
    assert [e["i"] for e in fr.tail(2)] == [8, 9]
    assert len(fr.tail(100)) == 4


def test_snapshot_does_not_drain():
    """A postmortem capture must not blind the next one."""
    fr = FlightRecorder(max_events=8)
    fr.record("a")
    assert len(fr.snapshot()) == 1
    assert len(fr.snapshot()) == 1


def test_events_carry_ts_kind_proc_and_fields():
    t = [100.0]
    fr = FlightRecorder(proc="w1", max_events=8, clock=lambda: t[0])
    fr.record("hop_send", rid=3, step=7, dest="w2")
    [e] = fr.snapshot()
    assert e == {"ts": 100.0, "kind": "hop_send", "proc": "w1",
                 "rid": 3, "step": 7, "dest": "w2"}


def test_process_default_recorder_is_shared_and_resettable():
    a = get_flight_recorder()
    a.record("x")
    assert get_flight_recorder() is a
    custom = FlightRecorder(max_events=2)
    set_flight_recorder(custom)
    assert get_flight_recorder() is custom


def test_thread_safety_totals():
    fr = FlightRecorder(max_events=64)

    def spam():
        for _ in range(500):
            fr.record("x")

    threads = [threading.Thread(target=spam) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert fr.total == 2000
    assert len(fr) == 64


def test_catalog_bridge_updates_flight_series():
    from distributed_inference_demo_tpu.telemetry.catalog import (
        FLIGHT_BUFFER, FLIGHT_EVENTS, update_flight_series)
    fr = FlightRecorder(max_events=4)
    set_flight_recorder(fr)
    for i in range(6):
        fr.record("x", i=i)
    update_flight_series()
    assert next(v for _, _, v in FLIGHT_EVENTS.samples()) == 6
    assert next(v for _, _, v in FLIGHT_BUFFER.samples()) == 4
