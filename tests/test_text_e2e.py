"""Text-in/text-out end to end: crafted sentencepiece .model → tokenizer →
HTTP server → multi-stage pipeline → decoded text.

This is the reference's whole user story (type text, watch generated text
stream back — ``BackgroundService.java:197-226`` feeding the ring, decode
via the attached tokenizer ``cpp/inference.cpp:88-94``), which no other
test covers jointly: test_sp_tokenizer covers the tokenizer alone,
test_cli the server alone, test_distributed the pipeline alone.
"""

import json
import http.client
import threading

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu import cli
from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import (
    slice_stage, split_layer_ranges)
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.distributed import (
    PipelineHeader, PipelineWorker, StageRuntime)
from distributed_inference_demo_tpu.runtime.http_server import (
    HeaderBackend, InferenceHTTPServer)
from distributed_inference_demo_tpu.sp_tokenizer import (
    CONTROL, NORMAL, UNKNOWN, build_model_proto)
from distributed_inference_demo_tpu.tokenizer import Tokenizer

MODEL = "llama-test"
GREEDY = SamplingParams(greedy=True)


@pytest.fixture(scope="module")
def sp_tokenizer(tmp_path_factory):
    """Mint a tiny unigram .model via the from-scratch protobuf writer.
    Every id stays < llama-test's vocab (256)."""
    words = ["hello", "world", "the", "cat", "sat", "on", "mat", "a"]
    pieces = [("<unk>", 0.0, UNKNOWN), ("<s>", 0.0, CONTROL),
              ("</s>", 0.0, CONTROL)]
    pieces += [(f"▁{w}", -float(i + 1), NORMAL)
               for i, w in enumerate(words)]
    # single-char pieces so any sampled id decodes to something
    import string
    pieces += [(c, -50.0, NORMAL) for c in string.ascii_lowercase]
    blob = build_model_proto(pieces)
    path = tmp_path_factory.mktemp("sp") / "tiny.model"
    path.write_bytes(blob)
    return path, Tokenizer.from_sentencepiece(blob)


@pytest.fixture(scope="module")
def served_pipeline(sp_tokenizer):
    """2-stage loopback pipeline behind the HTTP server with the crafted
    tokenizer attached."""
    _, tok = sp_tokenizer
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    t0, t1 = LoopbackTransport("s0", net), LoopbackTransport("s1", net)
    header = PipelineHeader(
        StageRuntime(cfg, specs[0], slice_stage(params, cfg, specs[0]), 64,
                     GREEDY),
        t0, next_id="s1", step_timeout=60)
    worker = PipelineWorker(
        StageRuntime(cfg, specs[1], slice_stage(params, cfg, specs[1]), 64,
                     GREEDY),
        t1, next_id=None, header_id="s0", step_timeout=60)
    th = threading.Thread(target=worker.serve_forever, daemon=True)
    th.start()
    backend = HeaderBackend(header, max_seq=64, num_stages=2)
    server = InferenceHTTPServer(backend, port=0, tokenizer=tok,
                                 model_name=MODEL)
    server.start()
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    yield server, tok, engine
    server.shutdown()
    header.shutdown_pipeline()
    th.join(timeout=30)


def _post(server, path, body):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=60)
    conn.request("POST", path, body=json.dumps(body),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


def test_sp_roundtrip(sp_tokenizer):
    _, tok = sp_tokenizer
    ids = tok.encode("hello world")
    assert len(ids) == 2                      # two whole-word pieces
    assert tok.decode(ids) == "hello world"


def test_text_to_text_over_pipeline(served_pipeline):
    """Prompt TEXT in → generated TEXT out, through sp tokenizer + HTTP +
    2-stage pipeline, matching the single-chip engine on the same ids."""
    server, tok, engine = served_pipeline
    status, data = _post(server, "/generate",
                         {"prompt": "the cat sat on the mat",
                          "max_new_tokens": 6})
    assert status == 200
    body = json.loads(data)

    ids = tok.encode("the cat sat on the mat")
    assert 1 <= len(ids) <= 16
    want = engine.generate(np.asarray([ids], np.int32), 6).tokens
    assert body["tokens"] == want.tolist()
    assert body["text"] == [tok.decode(row) for row in want.tolist()]


def test_text_streaming_over_pipeline(served_pipeline):
    server, tok, engine = served_pipeline
    status, data = _post(server, "/generate",
                         {"prompt": "hello world", "max_new_tokens": 4,
                          "stream": True})
    assert status == 200
    lines = [json.loads(l) for l in data.decode().strip().splitlines()]
    ids = tok.encode("hello world")
    want = engine.generate(np.asarray([ids], np.int32), 4).tokens
    token_lines = [l for l in lines if l["tokens"]]
    assert [l["tokens"][0] for l in token_lines] == want[0].tolist()
    # streamed text is INCREMENTAL: the concatenated deltas equal the
    # full-sequence decode (per-token decode would garble multi-token
    # UTF-8 and drop sentencepiece inter-token spaces)
    assert "".join(l["text"][0] for l in lines) == \
        tok.decode(want[0].tolist())


def test_chat_repl_text_against_pipeline(served_pipeline, monkeypatch,
                                         sp_tokenizer):
    """The chat REPL speaks TEXT against the tokenizer-attached pipeline
    server (reference chat loop, ChatScreen.kt) — and the same .model file
    loads through the CLI's --tokenizer path."""
    server, tok, engine = served_pipeline
    model_path, _ = sp_tokenizer

    import io
    from contextlib import redirect_stdout
    monkeypatch.setattr(cli.sys, "stdin", io.StringIO("hello world\n/quit\n"))
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["chat", "--url",
                       f"http://{server.host}:{server.port}",
                       "--max-new-tokens", "4",
                       "--template", "{msg}",
                       "--tokenizer", str(model_path)])
    assert rc == 0
    ids = tok.encode("hello world")
    want = engine.generate(np.asarray([ids], np.int32), 4).tokens
    # incremental detokenization renders the FULL-sequence decode (the
    # per-token join would drop sentencepiece's inter-token spaces)
    assert tok.decode(want[0].tolist()) in buf.getvalue()


def test_stop_sequences():
    """POST /generate {"stop": [...]}: rows end at the earliest stop
    string, which is excluded from the output (OpenAI convention);
    tokens truncate consistently with the text; unmatched requests
    report stop_reason "length" with the full output.  Uses a
    full-vocab-coverage tokenizer so every generated id decodes."""
    pieces = [("<unk>", 0.0, UNKNOWN), ("<s>", 0.0, CONTROL),
              ("</s>", 0.0, CONTROL)]
    pieces += [(f"\u2581w{i}", -float(i % 7 + 1), NORMAL)
               for i in range(253)]
    tok = Tokenizer.from_sentencepiece(build_model_proto(pieces))
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    server = InferenceHTTPServer(engine, port=0, tokenizer=tok,
                                 model_name=MODEL)
    server.start()
    try:
        prompt = [5, 17, 42, 7]
        want = engine.generate(np.asarray([prompt], np.int32),
                               8).tokens[0]
        want_text = tok.decode(want.tolist())
        assert len(want_text) >= 8
        mid = len(want_text) // 2
        stop_str = want_text[mid:mid + 3]
        assert stop_str

        status, data = _post(server, "/generate",
                             {"prompt_ids": [prompt],
                              "max_new_tokens": 8, "stop": [stop_str]})
        assert status == 200
        body = json.loads(data)
        assert body["stop_reason"] == ["stop"]
        assert stop_str not in body["text"][0]
        assert body["text"][0] == want_text[:want_text.find(stop_str)]
        # kept tokens PRODUCE the reported text (they may decode past
        # it at a held-back boundary, never short of it)
        assert tok.decode(body["tokens"][0]).startswith(body["text"][0])
        body_tokens, body_text = body["tokens"], body["text"][0]

        # no match anywhere -> full generation, reason "length"
        status, data = _post(server, "/generate",
                             {"prompt_ids": [prompt],
                              "max_new_tokens": 8,
                              "stop": ["\x00never\x00"]})
        body = json.loads(data)
        assert status == 200 and body["stop_reason"] == ["length"]
        assert body["tokens"][0] == want.tolist()
        assert body["text"][0] == want_text

        # STREAMING stop: emitted pieces concatenate to exactly the
        # blocking path's text (stop-prefix holdback — nothing the
        # client received is ever retracted), and the final line carries
        # the same truncated tokens + reasons
        status, data = _post(server, "/generate",
                             {"prompt_ids": [prompt],
                              "max_new_tokens": 8,
                              "stop": [stop_str], "stream": True})
        assert status == 200
        lines = [json.loads(l) for l in data.decode().splitlines()
                 if l.strip()]
        final = lines[-1]
        assert final.get("done") is True
        assert final["stop_reason"] == ["stop"]
        assert final["tokens"] == body_tokens
        streamed = "".join(l["text"][0] for l in lines[:-1])
        assert streamed == body_text
        assert stop_str not in streamed

        # bad stop lists are a clean 400
        status, _ = _post(server, "/generate",
                          {"prompt_ids": [prompt], "max_new_tokens": 2,
                           "stop": [""]})
        assert status == 400
    finally:
        server.shutdown()


# slow lane: stop-family refinement twin; test_stop_sequences and the
# chat-repl stop test keep the seam quick
@pytest.mark.slow
def test_stop_with_logprobs_truncates_rows_identically():
    """stop × logprobs, both paths (the 501 wall this combination used
    to hit is lifted): logprob rows truncate at EXACTLY the token index
    the stop truncates tokens — one cut, two parallel lists — and the
    values match the engine's full-row logprobs prefix-for-prefix."""
    pieces = [("<unk>", 0.0, UNKNOWN), ("<s>", 0.0, CONTROL),
              ("</s>", 0.0, CONTROL)]
    pieces += [(f"▁w{i}", -float(i % 7 + 1), NORMAL)
               for i in range(253)]
    tok = Tokenizer.from_sentencepiece(build_model_proto(pieces))
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    server = InferenceHTTPServer(engine, port=0, tokenizer=tok,
                                 model_name=MODEL)
    server.start()
    try:
        prompt = [5, 17, 42, 7]
        ref = engine.generate(np.asarray([prompt], np.int32), 8,
                              logprobs=True)
        want_toks = ref.tokens[0].tolist()
        want_lps = [round(float(x), 6) for x in ref.logprobs[0]]
        want_text = tok.decode(want_toks)
        mid = len(want_text) // 2
        stop_str = want_text[mid:mid + 3]

        # BLOCKING: rows truncate together
        status, data = _post(server, "/generate",
                             {"prompt_ids": [prompt],
                              "max_new_tokens": 8, "stop": [stop_str],
                              "logprobs": True})
        assert status == 200
        body = json.loads(data)
        assert body["stop_reason"] == ["stop"]
        kept = len(body["tokens"][0])
        assert 0 < kept < 8
        assert len(body["logprobs"][0]) == kept
        assert body["tokens"][0] == want_toks[:kept]
        assert body["logprobs"][0] == want_lps[:kept]

        # no match -> full rows, still aligned
        status, data = _post(server, "/generate",
                             {"prompt_ids": [prompt],
                              "max_new_tokens": 8,
                              "stop": ["\x00never\x00"],
                              "logprobs": True})
        body2 = json.loads(data)
        assert status == 200 and body2["stop_reason"] == ["length"]
        assert body2["logprobs"][0] == want_lps

        # STREAMING: the final line carries the SAME truncated pairs
        status, data = _post(server, "/generate",
                             {"prompt_ids": [prompt],
                              "max_new_tokens": 8, "stop": [stop_str],
                              "stream": True, "logprobs": True})
        assert status == 200
        lines = [json.loads(l) for l in data.decode().splitlines()
                 if l.strip()]
        final = lines[-1]
        assert final.get("done") is True
        assert final["tokens"] == body["tokens"]
        assert final["logprobs"] == body["logprobs"]
        assert final["stop_reason"] == ["stop"]
    finally:
        server.shutdown()


def test_stop_with_logprobs_needs_stream_logprob_backend():
    """A backend without streaming logprob support still gets a clean
    501 for the stop × logprobs combination (honor-or-reject)."""
    class NoLpBackend:
        eos_id = None

        def generate(self, ids, max_new, seed=0):
            raise AssertionError("unused")

        def generate_stream(self, ids, max_new, seed=0):
            raise AssertionError("unused")

    pieces = [("<unk>", 0.0, UNKNOWN), ("a", -1.0, NORMAL)]
    tok = Tokenizer.from_sentencepiece(build_model_proto(pieces))
    server = InferenceHTTPServer(NoLpBackend(), port=0, tokenizer=tok)
    server.start()
    try:
        status, data = _post(server, "/generate",
                             {"prompt_ids": [[1]], "max_new_tokens": 2,
                              "stop": ["x"], "logprobs": True})
        assert status == 501 and b"logprobs" in data
    finally:
        server.shutdown()


def test_stop_needs_tokenizer():
    """A tokenizer-less server rejects stop strings with a clean 501."""
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    server = InferenceHTTPServer(engine, port=0, model_name=MODEL)
    server.start()
    try:
        status, data = _post(server, "/generate",
                             {"prompt_ids": [[1, 2]],
                              "max_new_tokens": 2, "stop": ["x"]})
        assert status == 501 and b"tokenizer" in data
    finally:
        server.shutdown()


# slow lane: stop-family refinement twin; test_stop_sequences keeps the
# stop seam quick and eos accounting is pinned in test_device_loop
@pytest.mark.slow
def test_stop_reports_eos_reason():
    """A row that terminates on the backend's eos before any stop match
    reports stop_reason "eos" (not "length") and keeps only its real
    tokens — no eos padding accumulates while other rows run."""
    pieces = [("<unk>", 0.0, UNKNOWN), ("<s>", 0.0, CONTROL),
              ("</s>", 0.0, CONTROL)]
    pieces += [(f"▁w{i}", -float(i % 7 + 1), NORMAL)
               for i in range(253)]
    tok = Tokenizer.from_sentencepiece(build_model_proto(pieces))
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    plain = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    prompt = [5, 17, 42, 7]
    ref = plain.generate(np.asarray([prompt], np.int32), 8).tokens[0]
    eos = int(ref[3])                       # stops after 4 real tokens
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY,
                             eos_id=eos)
    server = InferenceHTTPServer(engine, port=0, tokenizer=tok,
                                 model_name=MODEL)
    server.start()
    try:
        status, data = _post(server, "/generate",
                             {"prompt_ids": [prompt],
                              "max_new_tokens": 8,
                              "stop": ["\x00never\x00"]})
        body = json.loads(data)
        assert status == 200 and body["stop_reason"] == ["eos"]
        assert body["tokens"][0] == ref[:4].tolist()
    finally:
        server.shutdown()


def test_chat_repl_with_stop(monkeypatch, tmp_path):
    """chat --stop renders the truncated text and survives the stream's
    final summary line (uses a full-vocab-coverage tokenizer so every
    generated id decodes)."""
    pieces = [("<unk>", 0.0, UNKNOWN), ("<s>", 0.0, CONTROL),
              ("</s>", 0.0, CONTROL)]
    pieces += [(f"▁w{i}", -float(i % 7 + 1), NORMAL)
               for i in range(253)]
    blob = build_model_proto(pieces)
    model_path = tmp_path / "full.model"
    model_path.write_bytes(blob)
    tok = Tokenizer.from_sentencepiece(blob)
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    server = InferenceHTTPServer(engine, port=0, tokenizer=tok,
                                 model_name=MODEL)
    server.start()
    try:
        prompt_text = "w5 w17"
        ids = tok.encode(prompt_text)
        want = engine.generate(np.asarray([ids], np.int32), 6).tokens
        full = tok.decode(want[0].tolist())
        mid = len(full) // 2
        stop_str = full[mid:mid + 2]
        assert stop_str

        import io
        from contextlib import redirect_stdout
        monkeypatch.setattr(cli.sys, "stdin",
                            io.StringIO(f"{prompt_text}\n/quit\n"))
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = cli.main(["chat", "--url",
                           f"http://{server.host}:{server.port}",
                           "--max-new-tokens", "6", "--template", "{msg}",
                           "--tokenizer", str(model_path),
                           "--stop", stop_str])
        assert rc == 0
        out = buf.getvalue()
        assert full[:full.find(stop_str)] in out
        assert full not in out            # the stop really truncated
    finally:
        server.shutdown()
