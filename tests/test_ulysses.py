"""Ulysses all-to-all sequence parallelism: generation parity vs the
single-device engine on the virtual CPU mesh, llama (RoPE/GQA) and bloom
(ALiBi, head-sliced slopes), plus the constraint checks."""

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
from distributed_inference_demo_tpu.parallel.ulysses import (
    make_ulysses_generate_fn)
from distributed_inference_demo_tpu.runtime import InferenceEngine

GREEDY = SamplingParams(greedy=True)


@pytest.mark.parametrize("model", [
    "llama-test",
    # tier-1 budget: llama-test is the quick-lane rep; the bloom
    # (alibi) twin rides the slow lane
    pytest.param("bloom-test", marks=pytest.mark.slow),
])
def test_ulysses_matches_engine(model, devices):
    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.asarray(
        np.random.RandomState(5).randint(0, cfg.vocab_size, (2, 8)),
        np.int32)
    want = InferenceEngine(cfg, params, max_seq=32,
                           sampling=GREEDY).generate(prompt, 6).tokens

    mesh = make_mesh(MeshConfig(sp=2), devices)
    gen = make_ulysses_generate_fn(cfg, mesh, max_seq=32, num_new_tokens=6,
                                   sampling=GREEDY)
    with mesh:
        got = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_ulysses_sp4(devices):
    """4-way: nh=4/nkv=2 llama-test cannot split kv 4 ways — bloom-test
    (nkv=4) can."""
    cfg = get_model_config("bloom-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.asarray(
        np.random.RandomState(7).randint(0, cfg.vocab_size, (1, 8)),
        np.int32)
    want = InferenceEngine(cfg, params, max_seq=32,
                           sampling=GREEDY).generate(prompt, 4).tokens
    mesh = make_mesh(MeshConfig(sp=4), devices)
    gen = make_ulysses_generate_fn(cfg, mesh, max_seq=32, num_new_tokens=4,
                                   sampling=GREEDY)
    with mesh:
        got = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_ulysses_rejects_bad_configs(devices):
    cfg = get_model_config("llama-test")        # nkv=2
    mesh4 = make_mesh(MeshConfig(sp=4), devices)
    with pytest.raises(ValueError, match="divisible"):
        make_ulysses_generate_fn(cfg, mesh4, max_seq=32, num_new_tokens=2)

    mesh2 = make_mesh(MeshConfig(sp=2), devices)
    gen = make_ulysses_generate_fn(cfg, mesh2, max_seq=16, num_new_tokens=4)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="not divisible"):
        gen(params, np.zeros((1, 7), np.int32), jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="max_seq"):
        gen(params, np.zeros((1, 14), np.int32), jax.random.PRNGKey(0))


# tier-1 budget: the ring fp8 twin (tests/test_sp_backend.py) is the
# quick-lane rep for fp8-cache x sequence-parallel
@pytest.mark.slow
def test_ulysses_fp8_cache_matches_fp8_engine(devices):
    """Reduced-precision head-sharded cache: greedy parity vs the fp8
    single-device engine (Ulysses attention already reads from the cache,
    so the contract needs no extra rounding step)."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.asarray(
        np.random.RandomState(13).randint(0, cfg.vocab_size, (2, 8)),
        np.int32)
    want = InferenceEngine(
        cfg, params, max_seq=32, sampling=GREEDY,
        kv_cache_dtype="float8_e4m3fn").generate(prompt, 6).tokens

    mesh = make_mesh(MeshConfig(sp=2), devices)
    gen = make_ulysses_generate_fn(cfg, mesh, max_seq=32, num_new_tokens=6,
                                   sampling=GREEDY,
                                   kv_cache_dtype="float8_e4m3fn")
    with mesh:
        got = np.asarray(gen(params, prompt, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_ulysses_fp8_rejects_pallas_backend(devices):
    """The one-owner reduced-precision rule also guards the sp paths: an
    explicit Pallas kernel request with a reduced cache dtype errors in
    resolve_cache_dtype_backend before any program is built."""
    from distributed_inference_demo_tpu.runtime.engine import (
        resolve_cache_dtype_backend)
    with pytest.raises(ValueError, match="attn_backend"):
        resolve_cache_dtype_backend("float8_e4m3fn", "flash")
