"""SentencePiece .model support: protobuf round-trip, unigram Viterbi,
score-BPE, byte fallback, facade integration.

Expectations are hand-derived (no sentencepiece library in the image); the
fixtures are real protobuf wire-format blobs produced by our own encoder, so
the parser is exercised on the same bytes layout sentencepiece writes
(``sentencepiece_model.proto`` field numbers).
"""

from pathlib import Path

import pytest

from distributed_inference_demo_tpu.sp_tokenizer import (
    BPE, BYTE, CONTROL, NORMAL, UNIGRAM, UNKNOWN, SPTokenizer,
    build_model_proto, parse_model_proto)
from distributed_inference_demo_tpu.tokenizer import Tokenizer


def unigram_pieces():
    # ids: 0 <unk>, 1 <s>, 2 </s>, then vocab
    pieces = [("<unk>", 0.0, UNKNOWN), ("<s>", 0.0, CONTROL),
              ("</s>", 0.0, CONTROL)]
    scored = [("▁", -3.0), ("a", -2.0), ("b", -2.0), ("c", -2.0),
              ("ab", -2.5), ("bc", -2.5), ("abc", -6.0), ("▁ab", -3.2),
              ("▁abc", -3.1)]
    pieces += [(p, s, NORMAL) for p, s in scored]
    return pieces


def test_proto_roundtrip():
    blob = build_model_proto(unigram_pieces(), model_type=UNIGRAM,
                             unk_id=0, bos_id=1, eos_id=2)
    m = parse_model_proto(blob)
    assert m.model_type == UNIGRAM
    assert (m.unk_id, m.bos_id, m.eos_id) == (0, 1, 2)
    assert m.add_dummy_prefix and m.escape_whitespaces
    assert m.pieces[0] == ("<unk>", 0.0, UNKNOWN)
    assert m.pieces[3][0] == "▁" and m.pieces[3][1] == pytest.approx(-3.0)
    assert len(m.pieces) == len(unigram_pieces())


def test_unigram_viterbi_picks_best_path():
    """"abc" normalizes to "▁abc". Candidate segmentations:
    [▁abc]=-3.1, [▁ab, c]=-5.2, [▁, abc]=-9.0, [▁, a, b, c]=-9.0, ...
    Viterbi must pick the single-piece path."""
    blob = build_model_proto(unigram_pieces())
    tok = SPTokenizer(parse_model_proto(blob))
    ids = tok.encode("abc")
    assert [tok.id_to_token(i) for i in ids] == ["▁abc"]

    # "abcbc": [▁abc, bc] = -3.1 - 2.5 = -5.6 beats [▁ab, c, bc] = -7.7
    ids = tok.encode("abcbc")
    assert [tok.id_to_token(i) for i in ids] == ["▁abc", "bc"]


def test_unigram_unknown_char_and_decode():
    blob = build_model_proto(unigram_pieces())
    tok = SPTokenizer(parse_model_proto(blob))
    ids = tok.encode("axb")   # x is not in the vocab -> unk id 0
    toks = [tok.id_to_token(i) for i in ids]
    assert toks == ["▁", "a", "<unk>", "b"]
    assert tok.decode(ids) == "ab"          # unk skipped on decode
    assert tok.decode(tok.encode("ab c")) == "ab c"


def test_bpe_by_score_merges_best_pair_first():
    """Score-BPE on "abc" (normalized "▁abc"): pair scores
    ab=-2.5, bc=-2.5 -> leftmost wins -> [▁, ab, c]; then ▁ab exists
    (-3.2) -> merges to [▁ab, c]; "abc" from (ab,c) is NOT a scored pair
    path beyond that (▁abc can't form from ▁ab + c? "▁abc" = -3.1 exists:
    merge continues) -> final [▁abc]."""
    blob = build_model_proto(unigram_pieces(), model_type=BPE)
    tok = SPTokenizer(parse_model_proto(blob))
    ids = tok.encode("abc")
    assert [tok.id_to_token(i) for i in ids] == ["▁abc"]

    # "cab": ▁cab -> pairs: (▁,c)=None, (c,a)=None, (a,b)=-2.5 -> [▁, c, ab]
    ids = tok.encode("cab")
    assert [tok.id_to_token(i) for i in ids] == ["▁", "c", "ab"]


def test_leading_space_round_trips():
    """sentencepiece prepends the dummy prefix unconditionally:
    ' ab' -> '▁▁ab' -> decode restores the leading space."""
    blob = build_model_proto(unigram_pieces())
    tok = SPTokenizer(parse_model_proto(blob))
    ids = tok.encode(" ab")
    assert tok.id_to_token(ids[0]) == "▁"
    assert tok.decode(ids) == " ab"


def test_bpe_heap_matches_bruteforce():
    """The O(n log n) heap merge must produce the same segmentation as the
    naive highest-score/leftmost scan."""
    import random
    blob = build_model_proto(unigram_pieces(), model_type=BPE)
    tok = SPTokenizer(parse_model_proto(blob))

    def brute(s):
        syms = list(s)
        while len(syms) > 1:
            best, bi = None, -1
            for i in range(len(syms) - 1):
                sc = tok.scores.get(syms[i] + syms[i + 1])
                if sc is not None and (best is None or sc > best):
                    best, bi = sc, i
            if best is None:
                break
            syms = syms[:bi] + [syms[bi] + syms[bi + 1]] + syms[bi + 2:]
        return syms

    rng = random.Random(0)
    for _ in range(50):
        s = "".join(rng.choice("abc ") for _ in range(rng.randrange(1, 40)))
        norm = tok._normalize(s)
        assert tok._segment_bpe(norm) == brute(norm), s


def test_byte_fallback():
    pieces = [("<unk>", 0.0, UNKNOWN), ("<s>", 0.0, CONTROL),
              ("</s>", 0.0, CONTROL)]
    pieces += [(f"<0x{b:02X}>", 0.0, BYTE) for b in range(256)]
    pieces += [("▁", -1.0, NORMAL), ("hi", -1.5, NORMAL)]
    blob = build_model_proto(pieces)
    m = parse_model_proto(blob)
    assert m.byte_fallback  # inferred from BYTE pieces
    tok = SPTokenizer(m)
    ids = tok.encode("hi é")  # é unknown -> utf-8 bytes C3 A9
    toks = [tok.id_to_token(i) for i in ids]
    assert toks[:3] == ["▁", "hi", "▁"]
    assert toks[3:] == ["<0xC3>", "<0xA9>"]
    assert tok.decode(ids) == "hi é"


def test_control_pieces_matched_as_specials():
    blob = build_model_proto(unigram_pieces())
    tok = SPTokenizer(parse_model_proto(blob))
    ids = tok.encode("<s>ab</s>")
    assert ids[0] == 1 and ids[-1] == 2
    assert tok.decode(ids, skip_special=False).startswith("<s>")
    assert "<s>" not in tok.decode(ids)


def test_facade_from_sentencepiece_and_from_file(tmp_path):
    blob = build_model_proto(unigram_pieces())
    path = tmp_path / "toy.model"
    path.write_bytes(blob)

    tok = Tokenizer.from_file(path)
    assert tok.backend == "sentencepiece"
    assert tok.bos_id == 1 and tok.eos_id == 2
    ids = tok.encode("abc", add_bos=True, add_eos=True)
    assert ids[0] == 1 and ids[-1] == 2
    assert tok.decode(ids) == "abc"
    assert tok.token_to_id("▁abc") >= 0
    assert tok.vocab_size() == len(unigram_pieces())
    assert tok.is_eos(2)
