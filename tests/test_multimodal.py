"""LLaVA-style multimodal stage (BASELINE config #5).

The pipeline composition — a vision encoder on its own transport node
(the "edge client"), decoder stages downstream — must produce exactly the
single-process MultimodalEngine's tokens; and with no image the multimodal
path must reduce to the plain text engine token for token.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import (
    slice_stage, split_layer_ranges)
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.models.vision import (
    VisionConfig, init_vision_params, vision_forward)
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.distributed import (
    PipelineWorker, StageRuntime)
from distributed_inference_demo_tpu.runtime.multimodal import (
    MultimodalEngine, MultimodalHeader, VisionWorker)

MODEL = "llama-test"
GREEDY = SamplingParams(greedy=True)
VCFG = VisionConfig(image_size=32, patch_size=16, hidden_size=32,
                    num_layers=2, num_heads=2, intermediate_size=64)


@pytest.fixture(scope="module")
def setup():
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    vparams = init_vision_params(jax.random.PRNGKey(1), VCFG,
                                 cfg.hidden_size)
    return cfg, params, vparams


def _image(b=1, seed=2):
    rng = np.random.RandomState(seed)
    return rng.randn(b, VCFG.image_size, VCFG.image_size,
                     VCFG.channels).astype(np.float32)


TEXT = np.array([[5, 17, 42, 7, 99]], dtype=np.int32)


def test_vision_forward_shape_and_determinism(setup):
    cfg, _, vparams = setup
    h1 = vision_forward(vparams, VCFG, jnp.asarray(_image()))
    h2 = vision_forward(vparams, VCFG, jnp.asarray(_image()))
    assert h1.shape == (1, VCFG.num_patches, cfg.hidden_size)
    np.testing.assert_array_equal(np.asarray(h1), np.asarray(h2))
    assert np.isfinite(np.asarray(h1)).all()


def test_image_changes_generation(setup):
    """The image prefix must actually condition decoding."""
    cfg, params, vparams = setup
    mm = MultimodalEngine(cfg, params, VCFG, vparams, max_seq=64,
                          sampling=GREEDY)
    t1 = mm.generate(_image(seed=2), TEXT, 8).tokens
    t2 = mm.generate(_image(seed=9) * 3.0, TEXT, 8).tokens
    assert not np.array_equal(t1, t2)


@pytest.mark.slow
def test_text_only_prefix_matches_plain_engine(setup):
    """Engine parity on the text-only suffix: a multimodal prefill whose
    prefix is exactly the token embeddings must reproduce the plain
    engine's greedy tokens."""
    cfg, params, vparams = setup
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    want = engine.generate(TEXT, 8).tokens

    mm = MultimodalEngine(cfg, params, VCFG, vparams, max_seq=64,
                          sampling=GREEDY)
    from distributed_inference_demo_tpu.models.decoder import embed_tokens
    embeds = embed_tokens(params, cfg, jnp.asarray(TEXT))
    cache = mm.engine.new_cache(1)
    logits, cache = mm._prefill_embeds(params, embeds, cache)
    toks, _, _ = mm.engine._decode(params, logits, cache,
                                   jax.random.PRNGKey(0),
                                   mm.engine._eos_scalar(), 8)
    np.testing.assert_array_equal(np.asarray(toks), want)


@pytest.mark.slow
def test_pipeline_vision_node_matches_engine(setup):
    """The VERDICT's done-bar: stage 0's vision encoder lives on its own
    transport node, decoder stages decode — tokens equal the single-process
    MultimodalEngine."""
    cfg, params, vparams = setup
    image = _image()
    mm = MultimodalEngine(cfg, params, VCFG, vparams, max_seq=64,
                          sampling=GREEDY)
    want = mm.generate(image, TEXT, 10).tokens

    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    th_, tv, tw = (LoopbackTransport(d, net) for d in ("s0", "vis", "s1"))
    header = MultimodalHeader(
        StageRuntime(cfg, specs[0], slice_stage(params, cfg, specs[0]), 64,
                     GREEDY),
        th_, next_id="s1", vision_id="vis", step_timeout=60)
    vision = VisionWorker(vparams, VCFG, tv, header_id="s0",
                          step_timeout=60)
    worker = PipelineWorker(
        StageRuntime(cfg, specs[1], slice_stage(params, cfg, specs[1]), 64,
                     GREEDY),
        tw, next_id=None, header_id="s0", step_timeout=60)
    threads = [threading.Thread(target=vision.serve_forever, args=(30,),
                                daemon=True),
               threading.Thread(target=worker.serve_forever, args=(30,),
                                daemon=True)]
    for t in threads:
        t.start()
    try:
        got = header.generate_mm(image, TEXT, 10)
        np.testing.assert_array_equal(got, want)
        # a second, text-only request through the same header still works
        engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
        got_text = header.generate(TEXT, 6)
        np.testing.assert_array_equal(got_text,
                                      engine.generate(TEXT, 6).tokens)
    finally:
        header.shutdown_pipeline()
        header.transport.send("vis", "stop", b"")
        for t in threads:
            t.join(timeout=30)
