"""Engine-side stream resumption (docs/DESIGN.md §23): bit-identity.

`submit_resumed` re-derives a dead replica's delivered prefix through
the NORMAL paged admission and streams only the suffix — so the
contract is the strongest one available: for every cut point k, the
delivered prefix plus the resumed suffix must equal the unfailed run
token-for-token, greedy AND sampled, across page dtypes, and with
speculation armed on the survivor.  A journal the survivor cannot
reproduce fails LOUDLY (never a silently-wrong stream), and the SLO
ledger books the replay window as a resume pause with the timeline
decomposition still summing exactly.
"""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)
from distributed_inference_demo_tpu.telemetry.slo import (SloLedger,
                                                          set_slo_ledger)

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)
SAMPLED = SamplingParams(temperature=0.9, top_k=40)
PROMPT = list(range(3, 24))
N = 10


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


def _engine(params, **kw):
    kw.setdefault("sampling", GREEDY)
    kw.setdefault("seed", 7)
    kw.setdefault("max_seq", 96)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prompt_buckets", (16, 48))
    kw.setdefault("kv_block_tokens", 8)
    return ContinuousBatchingEngine(CFG, params, **kw)


def _stream(eng, prompt=PROMPT, n=N, resume=None):
    ids = np.asarray(prompt, np.int32)[None, :]
    return [int(t[0]) for t in eng.generate_stream(ids, n, resume=resume)]


def _resume_at(eng, ref, k, prompt=PROMPT, n=N):
    resume = {"delivered_tokens": ref[:k], "rng_step_offset": k}
    return ref[:k] + _stream(eng, prompt, n, resume=resume)


def assert_no_leak(eng):
    mgr = eng.kv_cache
    assert mgr.used_blocks == mgr.tree.block_count, (
        mgr.used_blocks, mgr.tree.block_count)
    assert mgr.debug_state()["leased_nodes"] == 0


# ---------------------------------------------------------------------------
# bit-identity: greedy and sampled, every cut point
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_greedy_resume_bit_identical_and_zero_leak(params):
    """Greedy cuts at the edges and the middle: delivered + suffix ==
    the unfailed run, the replay never re-enters the stream, pages come
    back, and the resume ledger counts one request per cut."""
    with _engine(params) as eng:
        ref = _stream(eng)
        assert len(ref) == N
        # ONE warm survivor serves every cut: greedy replay is exact on
        # any survivor, busy or idle
        for i, k in enumerate((1, N // 2, N - 1), start=1):
            assert _resume_at(eng, ref, k) == ref, k
            st = eng.stats()["resumed"]
            assert st["requests"] == i and st["diverged"] == 0
        assert_no_leak(eng)


@pytest.mark.quick
def test_sampled_resume_every_cut_point_bit_identical(params):
    """The rng fast-forward property (ISSUE-20 satellite): for EVERY
    cut k in [1, n) a sampled stream resumes bit-identically — the
    survivor rewinds to the constructor seed and replays the original
    per-step split schedule, so the rng history of the cut is
    irrelevant."""
    with _engine(params, sampling=SAMPLED) as eng:
        ref = _stream(eng)
    with _engine(params, sampling=SAMPLED) as eng:
        for k in range(1, N):
            assert _resume_at(eng, ref, k) == ref, k
        st = eng.stats()["resumed"]
        assert st["requests"] == N - 1 and st["diverged"] == 0
        assert st["replayed_tokens"] == sum(range(1, N))
        assert_no_leak(eng)


@pytest.mark.parametrize("kv_dtype", [
    # tier-1 budget: the quantized twins ride the slow lane — the
    # quick-lane every-cut sampled test pins the resume contract on
    # bf16 pages, and §17 pins quantized-page exactness itself
    pytest.param("int8", marks=pytest.mark.slow),
    pytest.param("int4", marks=pytest.mark.slow),
])
def test_sampled_resume_over_quantized_pages(params, kv_dtype):
    """Quantized page pools change the logits, not the resume contract:
    reference and survivor share the page dtype and the sampled stream
    still cuts + resumes exactly."""
    with _engine(params, sampling=SAMPLED, kv_dtype=kv_dtype) as eng:
        ref = _stream(eng)
    with _engine(params, sampling=SAMPLED, kv_dtype=kv_dtype) as eng:
        for k in (1, N // 2, N - 1):
            assert _resume_at(eng, ref, k) == ref, (kv_dtype, k)
        assert_no_leak(eng)


# tier-1 budget: slow-lane twin — the quick greedy test pins resume
# bit-identity and the §22 suite pins greedy spec losslessness; this
# composes the two on a spec-armed survivor
@pytest.mark.slow
def test_greedy_resume_with_speculation_armed_on_survivor(params):
    """The survivor speculates, the dead replica did not: greedy spec
    is lossless, so the resumed suffix still matches the plain run —
    the replay rides the fused draft/verify dispatch like any other
    row."""
    with _engine(params) as eng:
        ref = _stream(eng)
    with _engine(params, prompt_lookup=True, num_draft=3,
                 prefill_chunk=8, decode_block=4) as eng:
        for k in (1, N - 2):
            assert _resume_at(eng, ref, k) == ref, k
        st = eng.stats()
        assert st["resumed"]["diverged"] == 0
        assert_no_leak(eng)


# ---------------------------------------------------------------------------
# failure semantics: loud divergence, validation, SLO accounting
# ---------------------------------------------------------------------------

@pytest.mark.quick
def test_divergent_journal_fails_loudly_not_silently(params):
    """A journal the survivor cannot re-derive (wrong token — torn
    fleet state, config skew) must FAIL the request at the first
    mismatched replay token, never stream a wrong suffix; the slot and
    pages come back and the engine keeps serving."""
    with _engine(params) as eng:
        ref = _stream(eng)
        bogus = [t + 1 for t in ref[:3]]       # never what argmax says
        req = eng.submit_resumed(PROMPT, N, bogus)
        with pytest.raises(RuntimeError, match="diverged"):
            req.wait(timeout=300)
        assert eng.stats()["resumed"]["diverged"] == 1
        # the engine survived: same prompt still answers bit-identically
        assert _stream(eng) == ref
        assert_no_leak(eng)


@pytest.mark.quick
def test_submit_resumed_validation(params):
    with _engine(params, eos_id=5) as eng:
        with pytest.raises(ValueError, match="at least one"):
            eng.submit_resumed(PROMPT, N, [])
        with pytest.raises(ValueError, match="nothing to resume"):
            eng.submit_resumed(PROMPT, 3, [7, 8, 9])
        with pytest.raises(ValueError, match="eos"):
            eng.submit_resumed(PROMPT, N, [7, 5])
        ids = np.asarray([PROMPT, PROMPT], np.int32)
        with pytest.raises(ValueError, match="single prompt row"):
            list(eng.generate_stream(
                ids, N, resume={"delivered_tokens": [7],
                                "rng_step_offset": 1}))


@pytest.mark.quick
def test_resume_pause_books_into_slo_decomposition(params):
    """The replay window lands in the ledger as resume_pause_s — the
    migration-pause analog — and the timeline decomposition still sums
    exactly: ttft + per_token*(n-1) + pauses == e2e."""
    led = SloLedger(ttft_slo_ms=10_000, tpot_slo_ms=10_000)
    set_slo_ledger(led)
    try:
        with _engine(params) as eng:
            ref = _stream(eng)
            assert _resume_at(eng, ref, N // 2) == ref
        recs = [r for r in led.recent(16) if r.get("resumed")]
        assert len(recs) == 1
        rec = recs[0]
        assert rec["resume_pause_s"] > 0.0
        lhs = (rec["ttft_s"] + rec["per_token_s"] * (rec["tokens"] - 1)
               + rec["migration_pause_s"] + rec["resume_pause_s"])
        assert lhs == pytest.approx(rec["e2e_s"], rel=1e-6)
        assert led.summary()["tenants"]["default"]["resumed"] == 1
    finally:
        set_slo_ledger(None)
