"""Prompt-lookup (draft-free) speculative decoding tests.

Greedy exactness is the load-bearing property: the n-gram proposer can
be arbitrarily wrong and the output must still be bit-identical to plain
greedy decode."""

import jax
import numpy as np
import pytest

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.prompt_lookup import (
    PromptLookupEngine)

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def oracle(params):
    return InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY)


def test_greedy_exactness(params, oracle):
    pld = PromptLookupEngine(CFG, params, max_seq=96, sampling=GREEDY,
                             num_draft=4)
    prompt = np.asarray([[3, 14, 15, 92, 65], [1, 2, 3, 4, 5]])
    want = oracle.generate(prompt, 24).tokens
    got, stats = pld.generate(prompt, 24)
    np.testing.assert_array_equal(want, got.tokens)
    assert stats.emitted == 24
    assert 0.0 <= stats.acceptance_rate <= 1.0


@pytest.mark.slow
def test_fp8_kv_greedy_matches_fp8_engine(params):
    """Prompt-lookup with an fp8 KV cache matches a plain engine at the
    SAME cache dtype bit-exactly (shared resolve_cache_dtype_backend
    rule: insert rounds, attention upcasts, jnp path forced)."""
    fp8_oracle = InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY,
                                 kv_cache_dtype="float8_e4m3fn")
    pld = PromptLookupEngine(CFG, params, max_seq=96, sampling=GREEDY,
                             num_draft=4,
                             kv_cache_dtype="float8_e4m3fn")
    prompt = np.asarray([[3, 14, 15, 92, 65]])
    want = fp8_oracle.generate(prompt, 16).tokens
    got, _ = pld.generate(prompt, 16)
    np.testing.assert_array_equal(want, got.tokens)
    with pytest.raises(ValueError, match="attn_backend"):
        PromptLookupEngine(CFG, params, max_seq=96, sampling=GREEDY,
                           attn_backend="flash",
                           kv_cache_dtype="float8_e4m3fn")


@pytest.mark.parametrize("plen", [
    pytest.param(5, marks=pytest.mark.slow),
    8,
    pytest.param(17, marks=pytest.mark.slow),
])
def test_chunked_prefill_matches_whole(params, oracle, plen):
    """prefill_chunk (C=8) must keep prompt-lookup decode bit-identical
    to whole-prompt prefill (the history buffer is host-seeded and
    unaffected by chunking)."""
    whole = PromptLookupEngine(CFG, params, max_seq=64, sampling=GREEDY,
                               num_draft=4)
    chunked = PromptLookupEngine(CFG, params, max_seq=64, sampling=GREEDY,
                                 num_draft=4, prefill_chunk=8)
    prompt = (np.arange(plen).reshape(1, plen) % 199).astype(np.int32)
    want, _ = whole.generate(prompt, 10)
    got, _ = chunked.generate(prompt, 10)
    np.testing.assert_array_equal(want.tokens, got.tokens)


@pytest.mark.slow
def test_lookup_accelerates_self_repetition(params, oracle):
    """Greedy decode of a tiny random model falls into loops; once the
    loop is in the history the lookup proposer should ride it, emitting
    > 1 token per round on average."""
    base = [3, 14, 15, 92]
    cont = oracle.generate(np.asarray([base]), 12).tokens[0]
    # seed the prompt with the model's own continuation: generation
    # repeats text that is now literally in the prompt
    prompt = np.asarray([base + cont.tolist()])
    pld = PromptLookupEngine(CFG, params, max_seq=96, sampling=GREEDY,
                             num_draft=4)
    want = oracle.generate(prompt, 20).tokens
    got, stats = pld.generate(prompt, 20)
    np.testing.assert_array_equal(want, got.tokens)
    assert stats.tokens_per_round > 1.0, stats


def test_dispatch_size_invariance(params, oracle):
    pld = PromptLookupEngine(CFG, params, max_seq=96, sampling=GREEDY,
                             num_draft=3)
    prompt = np.asarray([[7, 8, 9]])
    a, _ = pld.generate(prompt, 17, rounds_per_dispatch=1)
    b, _ = pld.generate(prompt, 17, rounds_per_dispatch=8)
    np.testing.assert_array_equal(a.tokens, b.tokens)


def test_sampled_mode(params):
    pld = PromptLookupEngine(CFG, params, max_seq=96,
                             sampling=SamplingParams(temperature=0.8,
                                                     top_k=7),
                             num_draft=4)
    prompt = np.asarray([[3, 14, 15], [9, 2, 6]])
    res, stats = pld.generate(prompt, 20, seed=3)
    assert res.tokens.shape == (2, 20)
    assert (res.tokens >= 0).all() and (res.tokens < CFG.vocab_size).all()
    # deterministic per seed
    res2, _ = pld.generate(prompt, 20, seed=3)
    np.testing.assert_array_equal(res.tokens, res2.tokens)


def test_stream_matches_generate(params):
    pld = PromptLookupEngine(CFG, params, max_seq=96, sampling=GREEDY,
                             num_draft=3)
    prompt = np.asarray([[3, 14, 15], [9, 2, 6]])
    blocking, _ = pld.generate(prompt, 15)
    streamed = np.stack(list(pld.generate_stream(prompt, 15)), axis=1)
    np.testing.assert_array_equal(blocking.tokens, streamed)
    assert list(pld.generate_stream(prompt, 0)) == []


def test_http_serve_backend(params, oracle):
    """serve --prompt-lookup's backend: /generate + /stats over HTTP."""
    import http.client
    import json

    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)
    from distributed_inference_demo_tpu.runtime.speculative import (
        SpeculativeBackend)

    backend = SpeculativeBackend(PromptLookupEngine(
        CFG, params, max_seq=96, sampling=GREEDY, num_draft=3))
    server = InferenceHTTPServer(backend, port=0, model_name="llama-test")
    server.start()
    try:
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=300)
        prompt = [[5, 17, 42, 7]]
        conn.request("POST", "/generate",
                     json.dumps({"prompt_ids": prompt,
                                 "max_new_tokens": 9}),
                     {"Content-Type": "application/json"})
        out = json.loads(conn.getresponse().read())
        want = oracle.generate(np.asarray(prompt), 9).tokens.tolist()
        assert out["tokens"] == want
        conn.request("GET", "/stats", headers={})
        stats = json.loads(conn.getresponse().read())
        assert stats["speculative"]["rounds"] >= 1
        conn.close()
    finally:
        server.shutdown()


@pytest.mark.slow
def test_tp_mesh_parity(params, oracle):
    """Prompt lookup over a tp=2 mesh: greedy output equals the plain
    single-device engine (TP + speculation compose)."""
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)

    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    pld = PromptLookupEngine(CFG, shard_engine_params(params, CFG, mesh),
                             max_seq=96, sampling=GREEDY, num_draft=3,
                             mesh=mesh)
    prompt = np.asarray([[3, 14, 15, 92, 65]])
    want = oracle.generate(prompt, 14).tokens
    got, _ = pld.generate(prompt, 14)
    np.testing.assert_array_equal(want, got.tokens)


# tier-1 budget: quantized-weights x engine keeps quick reps in
# test_parallel (pipeline_quantized_params) and the checkpoint
# int8 roundtrips; this pld twin rides the slow lane
@pytest.mark.slow
def test_int8_weights(params):
    """Quantized target params work through the lookup engine (greedy
    parity vs the int8 plain engine)."""
    cfg8 = get_model_config("llama-test-int8")
    params8 = init_full_params(jax.random.PRNGKey(0), cfg8, quantize=True)
    oracle8 = InferenceEngine(cfg8, params8, max_seq=96, sampling=GREEDY)
    pld = PromptLookupEngine(cfg8, params8, max_seq=96, sampling=GREEDY,
                             num_draft=3)
    prompt = np.asarray([[3, 14, 15, 92, 65]])
    want = oracle8.generate(prompt, 12).tokens
    got, _ = pld.generate(prompt, 12)
    np.testing.assert_array_equal(want, got.tokens)


def test_capacity_and_validation(params):
    with pytest.raises(ValueError, match="num_draft"):
        PromptLookupEngine(CFG, params, num_draft=0)
    pld = PromptLookupEngine(CFG, params, max_seq=32, sampling=GREEDY)
    with pytest.raises(ValueError, match="exceeds"):
        pld.generate(np.zeros((1, 30), np.int64), 10)


@pytest.mark.slow
def test_eos_padding_matches_engine(params):
    """With eos_id set, greedy PLD equals InferenceEngine's eos-padded
    fused scan bit-exactly."""
    sampling = SamplingParams(greedy=True)
    base = InferenceEngine(CFG, params, max_seq=160, sampling=sampling)
    prompt = np.asarray([[3, 14, 15, 92, 65, 3, 14, 15]])
    plain = base.generate(prompt, 24).tokens
    eos = int(plain[0, 4])
    base_eos = InferenceEngine(CFG, params, max_seq=160, sampling=sampling,
                               eos_id=eos)
    want = base_eos.generate(prompt, 24).tokens
    pld = PromptLookupEngine(CFG, params, max_seq=160, sampling=sampling,
                             num_draft=4, eos_id=eos)
    got, _ = pld.generate(prompt, 24)
    np.testing.assert_array_equal(want, got.tokens)


@pytest.mark.slow
def test_eos_stream_matches_engine_stream(params):
    sampling = SamplingParams(greedy=True)
    base = InferenceEngine(CFG, params, max_seq=160, sampling=sampling)
    prompt = np.asarray([[3, 14, 15, 92, 65, 3, 14, 15]])
    plain = base.generate(prompt, 24).tokens
    eos = int(plain[0, 4])
    base_eos = InferenceEngine(CFG, params, max_seq=160, sampling=sampling,
                               eos_id=eos)
    want = list(base_eos.generate_stream(prompt, 24))
    pld = PromptLookupEngine(CFG, params, max_seq=160, sampling=sampling,
                             num_draft=4, eos_id=eos)
    got = list(pld.generate_stream(prompt, 24))
    assert len(want) == len(got)
    np.testing.assert_array_equal(np.stack(want), np.stack(got))
