"""Pallas flash-attention kernel vs the jnp reference (interpret mode).

The kernel must be numerically interchangeable with ``ops.attention`` for
every engine-visible configuration: prefill chunks, single-token decode,
GQA grouping, ALiBi bias, partial caches, and multi-block row/kv tiling.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_demo_tpu.models import (
    KVCache, StageSpec, get_model_config)
from distributed_inference_demo_tpu.models.decoder import (
    init_full_params, stage_forward)
from distributed_inference_demo_tpu.ops.attention import (
    alibi_slopes, attention)
from distributed_inference_demo_tpu.ops.flash_attention import (
    _pick_block, flash_attention, make_flash_attn_impl)


def test_pick_block_respects_sublane_alignment():
    """ADVICE r1 #2: block_k is a sublane dimension — it must be a multiple
    of 8, never an arbitrary divisor (1000 -> 125 was the bug)."""
    assert _pick_block(1000, 128) == 40        # not 125
    assert _pick_block(2048, 128) == 128
    assert _pick_block(64, 128) == 64
    assert _pick_block(24, 16) == 16 or _pick_block(24, 16) == 8
    for total in (8, 16, 40, 128, 1000, 2048):
        b = _pick_block(total, 128)
        assert total % b == 0 and b % 8 == 0
    with pytest.raises(ValueError, match="divisible by 8"):
        _pick_block(1001, 128)


def test_flash_odd_max_seq_multiple_of_8():
    """A max_seq like 1000 (divisible by 8, not by 128) must pick an
    aligned block and still match the reference."""
    rng = np.random.RandomState(0)
    b, chunk, nh, nkv, hd, max_seq, q_start = 1, 8, 4, 2, 16, 1000, 4
    kv_len = q_start + chunk
    q = jnp.asarray(rng.randn(b, chunk, nh, hd), jnp.float32)
    kc = jnp.asarray(rng.randn(b, nkv, max_seq, hd), jnp.float32)
    vc = jnp.asarray(rng.randn(b, nkv, max_seq, hd), jnp.float32)
    mask = (np.arange(max_seq) < kv_len)[None, None, :, None]
    kc, vc = kc * mask, vc * mask
    expected = _reference(q, kc, vc, q_start, kv_len, None)
    got = flash_attention(q, kc, vc, jnp.asarray(q_start, jnp.int32),
                          jnp.asarray(kv_len, jnp.int32), None,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def _reference(q, kc, vc, q_start, kv_len, slopes):
    b, chunk = q.shape[0], q.shape[1]
    q_pos = jnp.broadcast_to(q_start + jnp.arange(chunk), (b, chunk))
    return attention(q, kc, vc, q_pos, jnp.asarray(kv_len, jnp.int32), slopes)


@pytest.mark.parametrize(
    "b,chunk,nh,nkv,hd,max_seq,q_start,alibi",
    [
        (2, 8, 4, 2, 16, 64, 0, False),     # prefill from empty, GQA
        (2, 1, 4, 2, 16, 64, 23, False),    # decode mid-cache
        (1, 16, 4, 4, 16, 64, 8, False),    # chunked prefill, MHA
        (2, 8, 4, 4, 64, 128, 0, True),     # ALiBi (bloom: no GQA)
        (1, 1, 8, 2, 16, 256, 100, False),  # decode, multi-kv-block cache
        (1, 64, 8, 8, 16, 64, 0, False),    # multiple row blocks
    ])
def test_flash_matches_reference(b, chunk, nh, nkv, hd, max_seq, q_start,
                                 alibi):
    rng = np.random.RandomState(0)
    kv_len = q_start + chunk
    q = jnp.asarray(rng.randn(b, chunk, nh, hd), jnp.float32)
    # head-major cache layout [b, nkv, max_seq, hd]
    kc = jnp.asarray(rng.randn(b, nkv, max_seq, hd), jnp.float32)
    vc = jnp.asarray(rng.randn(b, nkv, max_seq, hd), jnp.float32)
    # zero out the unfilled region to make intent explicit (masked anyway)
    mask = (np.arange(max_seq) < kv_len)[None, None, :, None]
    kc = kc * mask
    vc = vc * mask
    slopes = alibi_slopes(nh) if alibi else None

    expected = _reference(q, kc, vc, q_start, kv_len, slopes)
    got = flash_attention(q, kc, vc, jnp.asarray(q_start, jnp.int32),
                          jnp.asarray(kv_len, jnp.int32), slopes,
                          block_k=32, block_rows_target=32, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("model", [
    "llama-test",
    pytest.param("bloom-test", marks=pytest.mark.slow),
])
def test_flash_attn_impl_generation_parity(model):
    """Whole-model greedy generation: flash attn_impl == default path."""
    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    spec = StageSpec(0, 1, 0, cfg.num_layers)
    b, plen, steps, max_seq = 2, 8, 4, 32
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, cfg.vocab_size, (b, plen)),
        jnp.int32)

    def generate(attn_impl):
        cache = KVCache.create(cfg, cfg.num_layers, b, max_seq)
        pos = jnp.broadcast_to(jnp.arange(plen), (b, plen))
        logits, cache = stage_forward(params, cfg, spec, prompt, cache, pos,
                                      attn_impl=attn_impl)
        toks = [jnp.argmax(logits[:, -1], -1).astype(jnp.int32)]
        for i in range(steps - 1):
            p = jnp.full((b, 1), plen + i, jnp.int32)
            logits, cache = stage_forward(params, cfg, spec,
                                          toks[-1][:, None], cache, p,
                                          attn_impl=attn_impl)
            toks.append(jnp.argmax(logits[:, -1], -1).astype(jnp.int32))
        return np.stack([np.asarray(t) for t in toks], 1)

    base = generate(None)
    # min_chunk=1 forces every chunk (incl. decode) through the kernel
    flash = generate(make_flash_attn_impl(interpret=True, min_chunk=1))
    np.testing.assert_array_equal(base, flash)
