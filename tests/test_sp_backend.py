"""``serve --sp`` — the long-context serving surface.

Greedy output through the sequence-parallel HTTP backend must be
bit-identical to the plain single-device engine (the repo's standing
oracle), bad prompt lengths must surface as clean HTTP 400s (never a
silent server-side pad), and the CLI's mode pairing rules must reject
--sp against every other serve mode.
"""

import http.client
import json

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu import cli
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.parallel.mesh import local_sp_mesh
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.http_server import (
    InferenceHTTPServer)
from distributed_inference_demo_tpu.runtime.sp_backend import (
    SequenceParallelBackend)

GREEDY = SamplingParams(greedy=True)


def _req(server, method, path, body=None):
    conn = http.client.HTTPConnection(server.host, server.port, timeout=60)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    conn.close()
    return resp.status, data


@pytest.fixture(scope="module", params=["ring", "ulysses"])
def sp_server(request):
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    plain = InferenceEngine(cfg, params, max_seq=32, sampling=GREEDY)
    backend = SequenceParallelBackend(
        cfg, params, local_sp_mesh(2), max_seq=32,
        strategy=request.param, sampling=GREEDY)
    server = InferenceHTTPServer(backend, port=0, model_name="llama-test")
    server.start()
    yield server, plain, backend
    server.shutdown()


@pytest.mark.quick
def test_sp_serve_matches_plain_engine(sp_server):
    server, plain, _ = sp_server
    prompt = [[5, 17, 42, 7, 9, 2, 30, 11]]       # len 8, divides sp=2
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": prompt, "max_new_tokens": 4})
    assert status == 200
    got = json.loads(data)["tokens"]
    want = plain.generate(np.asarray(prompt), 4).tokens.tolist()
    assert got == want


def test_sp_serve_rejects_indivisible_prompt(sp_server):
    server, _, _ = sp_server
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": [[1, 2, 3]], "max_new_tokens": 4})
    assert status == 400
    assert "divisible" in json.loads(data)["error"]


def test_sp_serve_rejects_over_capacity(sp_server):
    server, _, _ = sp_server
    status, data = _req(server, "POST", "/generate",
                        {"prompt_ids": [list(range(30))],
                         "max_new_tokens": 10})
    assert status == 400
    assert "max_seq" in json.loads(data)["error"]


def test_sp_serve_stats(sp_server):
    server, _, backend = sp_server
    status, data = _req(server, "GET", "/stats")
    assert status == 200
    body = json.loads(data)
    assert body["mode"] == "sequence_parallel"
    assert body["sp"] == 2
    assert body["strategy"] == backend.strategy
    # queue picture: idle server -> empty line, bound surfaced
    assert body["queue_depth"] == 0
    assert body["busy"] is False
    assert body["queue_bound"] == backend.max_queue_depth


def _req_h(server, method, path, body=None):
    """_req + response headers (Retry-After assertions)."""
    conn = http.client.HTTPConnection(server.host, server.port,
                                      timeout=60)
    conn.request(method, path,
                 body=json.dumps(body) if body is not None else None,
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    data = resp.read()
    headers = dict(resp.getheaders())
    conn.close()
    return resp.status, headers, data


def test_sp_queue_two_clients_visibility_and_429():
    """The VERDICT r5 item-5 scenario: while one long-context request
    holds the sp device lock, a second client sees the line on /stats
    (queue_depth/busy) and — past the configured bound — gets an
    immediate 429 + Retry-After instead of silently blocking on
    ``_lock`` for potentially minutes."""
    import threading
    import time

    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    backend = SequenceParallelBackend(
        cfg, params, local_sp_mesh(2), max_seq=32, strategy="ring",
        sampling=GREEDY, max_queue_depth=1)
    server = InferenceHTTPServer(backend, port=0, model_name="llama-test")
    server.start()
    prompt = {"prompt_ids": [[5, 17, 42, 7, 9, 2, 30, 11]],
              "max_new_tokens": 2}
    try:
        # a "long-context request" occupies the device: admitted AND
        # holding the lock (deterministic stand-in for minutes of sp
        # compute — the admission API is exactly what a request uses)
        backend._admit()
        backend._lock.acquire()
        try:
            results = {}
            t = threading.Thread(
                target=lambda: results.update(
                    a=_req(server, "POST", "/generate", prompt)),
                daemon=True)
            t.start()             # client A: admitted, waits in line
            deadline = time.monotonic() + 30
            while True:
                body = json.loads(_req(server, "GET", "/stats")[1])
                if body["queue_depth"] >= 1:
                    break
                assert time.monotonic() < deadline, "A never queued"
                time.sleep(0.02)
            assert body["busy"] is True
            assert body["queue_bound"] == 1
            # client B: the line is full -> 429 NOW, with Retry-After
            status, headers, data = _req_h(server, "POST", "/generate",
                                           prompt)
            assert status == 429
            assert int(headers["Retry-After"]) >= 1
            assert "queue full" in json.loads(data)["error"]
            # streaming client: same rejection, clean pre-header 429
            status, headers, _ = _req_h(
                server, "POST", "/generate", dict(prompt, stream=True))
            assert status == 429
            assert "Retry-After" in headers
        finally:
            backend._lock.release()
        t.join(timeout=60)
        assert results["a"][0] == 200     # the queued client completed
        backend._leave()                  # the stand-in request's exit
        body = json.loads(_req(server, "GET", "/stats")[1])
        assert body["queue_depth"] == 0 and body["busy"] is False
    finally:
        server.shutdown()


def test_sp_serve_streaming(sp_server):
    """stream: true works against serve --sp (the chat REPL always
    streams); tokens arrive as JSONL steps and match the plain engine."""
    server, plain, _ = sp_server
    prompt = [[5, 17, 42, 7, 9, 2, 30, 11]]
    conn = http.client.HTTPConnection(server.host, server.port, timeout=60)
    conn.request("POST", "/generate",
                 body=json.dumps({"prompt_ids": prompt,
                                  "max_new_tokens": 4, "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    lines = [json.loads(line) for line in resp.read().decode().splitlines()
             if line.strip()]
    conn.close()
    got = [line["tokens"][0] for line in lines]
    want = plain.generate(np.asarray(prompt), 4).tokens[0].tolist()
    assert got == want


def test_sp_backend_rejects_bad_config_at_construction():
    """A misconfigured server must fail BEFORE HTTP_READY, not 400
    every client: max_seq not divisible by sp errors in __init__."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="divisible"):
        SequenceParallelBackend(cfg, params, local_sp_mesh(2),
                                max_seq=33, sampling=GREEDY)


def test_sp_backend_bounds_compiled_variants(sp_server):
    _, _, backend = sp_server
    for n in range(1, backend.MAX_COMPILED_VARIANTS + 3):
        backend._fn(n)
    assert len(backend._fns) == backend.MAX_COMPILED_VARIANTS


def test_sp_serve_mode_pairing_rules(capsys):
    base = ["serve", "--model", "llama-test", "--sp", "2"]
    assert cli.main(base + ["--batch-slots", "2"]) == 1
    assert cli.main(base + ["--draft-model", "llama-test"]) == 1
    assert cli.main(base + ["--prompt-lookup"]) == 1
    assert cli.main(base + ["--chain", "w@127.0.0.1:1"]) == 1
    assert cli.main(base + ["--tp", "2"]) == 1
    assert cli.main(base + ["--prefill-chunk", "4"]) == 1
    assert cli.main(base + ["--stream-block", "4"]) == 1
    err = capsys.readouterr().err
    assert "--prefill-chunk" in err
    assert "--stream-block" in err


@pytest.mark.parametrize("strategy", [
    "ring", pytest.param("ulysses", marks=pytest.mark.slow)])
def test_sp_backend_fp8_cache_matches_fp8_engine(strategy):
    """serve --sp --kv-cache-dtype: the backend's reduced-precision cache
    matches the fp8 single-device engine token for token."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.asarray([[5, 17, 42, 7, 9, 2, 30, 11]], np.int32)
    want = InferenceEngine(
        cfg, params, max_seq=32, sampling=GREEDY,
        kv_cache_dtype="float8_e4m3fn").generate(prompt, 6).tokens
    backend = SequenceParallelBackend(
        cfg, params, local_sp_mesh(2), max_seq=32, strategy=strategy,
        sampling=GREEDY, kv_cache_dtype="float8_e4m3fn")
    got = backend.generate(prompt, 6).tokens
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("strategy", [
    "ring", pytest.param("ulysses", marks=pytest.mark.slow)])
def test_sp_stream_fns_greedy_parity_and_partial_block(strategy):
    """The step-split stream path is bit-identical to the fused
    generate() for greedy decoding, including a final PARTIAL block
    (num_new % block != 0) and the capacity edge plen + num_new ==
    max_seq (surplus scan steps write only into discarded slots)."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    backend = SequenceParallelBackend(
        cfg, params, local_sp_mesh(2), max_seq=32, strategy=strategy,
        sampling=GREEDY)
    backend.STREAM_BLOCK = 4
    prompt = np.asarray(
        np.random.RandomState(3).randint(0, cfg.vocab_size, (1, 16)),
        np.int32)
    for num_new in (3, 6, 13, 16):      # < block, partial, multi, == cap
        want = backend.generate(prompt, num_new).tokens
        got = np.stack(
            list(backend.generate_stream(prompt, num_new)), axis=1)
        np.testing.assert_array_equal(got, want)


# slow lane: stream twin of test_sp_backend_fp8_cache_matches_fp8_engine;
# stream parity itself stays quick via test_sp_stream_fns_greedy_parity
@pytest.mark.slow
def test_sp_stream_fp8_cache_matches_fp8_engine():
    """Streaming composes with the reduced-precision sp cache."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.asarray([[5, 17, 42, 7, 9, 2, 30, 11]], np.int32)
    want = InferenceEngine(
        cfg, params, max_seq=32, sampling=GREEDY,
        kv_cache_dtype="float8_e4m3fn").generate(prompt, 6).tokens
    backend = SequenceParallelBackend(
        cfg, params, local_sp_mesh(2), max_seq=32, strategy="ring",
        sampling=GREEDY, kv_cache_dtype="float8_e4m3fn")
    backend.STREAM_BLOCK = 4
    got = np.stack(list(backend.generate_stream(prompt, 6)), axis=1)
    np.testing.assert_array_equal(got, want)


# tier-1 budget: stream_fns greedy parity [ring] keeps the quick rep
@pytest.mark.slow
def test_sp_stream_is_incremental():
    """One compiled pair serves every max_new_tokens, and the first
    token arrives after ONE prefill dispatch (the generator yields
    before any decode block runs)."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    backend = SequenceParallelBackend(
        cfg, params, local_sp_mesh(2), max_seq=32, strategy="ring",
        sampling=GREEDY)
    backend.STREAM_BLOCK = 4
    prompt = np.asarray([[5, 17, 42, 7, 9, 2, 30, 11]], np.int32)
    gen = backend.generate_stream(prompt, 12)
    first = next(gen)
    assert first.shape == (1,)
    gen.close()                          # abandon mid-stream: lock freed
    # the backend is still serviceable after an abandoned stream
    res = backend.generate(prompt, 4)
    assert res.tokens.shape == (1, 4)
    # different max_new values reuse the one compiled pair
    assert backend._stream_pair is not None
    got6 = np.stack(list(backend.generate_stream(prompt, 6)), axis=1)
    got9 = np.stack(list(backend.generate_stream(prompt, 9)), axis=1)
    np.testing.assert_array_equal(got6, got9[:, :6])


@pytest.mark.parametrize("strategy", [
    "ring", pytest.param("ulysses", marks=pytest.mark.slow)])
def test_sp_backend_eos_matches_engine_and_stops_early(strategy):
    """eos on the sp backend: generate() pads finished rows with eos
    exactly like the single-device engine, and the stream stops
    dispatching once every row finished (fewer yielded steps)."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.asarray([[5, 17, 42, 7, 9, 2, 30, 11]], np.int32)
    # choose the 3rd greedy token as eos: stop arrives mid-generation
    ref = InferenceEngine(cfg, params, max_seq=32,
                          sampling=GREEDY).generate(prompt, 10).tokens
    eos = int(ref[0, 2])
    want = InferenceEngine(cfg, params, max_seq=32, sampling=GREEDY,
                           eos_id=eos).generate(prompt, 10).tokens
    backend = SequenceParallelBackend(
        cfg, params, local_sp_mesh(2), max_seq=32, strategy=strategy,
        sampling=GREEDY, eos_id=eos)
    backend.STREAM_BLOCK = 4
    got = backend.generate(prompt, 10)
    np.testing.assert_array_equal(got.tokens, want)
    steps = list(backend.generate_stream(prompt, 10))
    assert len(steps) == 3 and int(steps[-1][0]) == eos
    np.testing.assert_array_equal(np.stack(steps, axis=1), want[:, :3])


@pytest.mark.parametrize("strategy", ["ring"])
def test_sp_backend_instant_eos_reports_prefill_seconds(strategy):
    """ADVICE r5: a generation that ends at (or right after) prefill —
    num_new=1, or eos on the very first token — must report the prefill
    dispatch's seconds, not 0.0/NaN (the box is flushed right after the
    prefill dispatch, not only after decode blocks)."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.asarray([[5, 17, 42, 7, 9, 2, 30, 11]], np.int32)
    first = int(InferenceEngine(cfg, params, max_seq=32, sampling=GREEDY)
                .generate(prompt, 1).tokens[0, 0])
    backend = SequenceParallelBackend(
        cfg, params, local_sp_mesh(2), max_seq=32, strategy=strategy,
        sampling=GREEDY, eos_id=first)       # eos == token #1: instant stop
    res = backend.generate(prompt, 10)
    assert res.tokens[0, 0] == first
    assert res.seconds > 0.0
    assert res.tokens_per_second == res.tokens_per_second  # not NaN
    # num_new=1 (prefill-only generation) times the same way
    res1 = backend.generate(prompt, 1)
    assert res1.seconds > 0.0
