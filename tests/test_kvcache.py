"""Block-level KV cache (runtime/kvcache): radix-tree properties against
a brute-force reference, refcount/CoW + eviction invariants, byte
accounting, and cold-vs-primed EXACTNESS through the single-request
engines (ISSUE 3 acceptance: cached-vs-cold generations are
token-identical; eviction honors live leases).

The tree/pool/manager tests run host-only (numpy in, numpy out — no jax
below the manager); the exactness tests drive real engines on tiny
models.
"""

import numpy as np
import pytest

from distributed_inference_demo_tpu.runtime.kvcache import (
    KVBlockPool, KVCacheManager, RadixTree)

# ---------------------------------------------------------------------------
# radix tree vs brute-force reference


def _keys(tokens, bt):
    return [tuple(tokens[i * bt:(i + 1) * bt])
            for i in range(len(tokens) // bt)]


class BruteForce:
    """Reference model: a bag of stored block-key sequences; the longest
    common block-prefix over the bag is the ground truth for match."""

    def __init__(self):
        self.seqs = []

    def insert(self, keys):
        self.seqs.append(list(keys))

    def match_len(self, keys):
        best = 0
        for seq in self.seqs:
            n = 0
            while (n < len(seq) and n < len(keys)
                   and seq[n] == keys[n]):
                n += 1
            best = max(best, n)
        return best


@pytest.mark.quick
def test_radix_match_equals_bruteforce_on_random_workload():
    rng = np.random.default_rng(0)
    bt = 4
    tree, ref = RadixTree(), BruteForce()
    next_id = [0]

    def alloc(_):
        next_id[0] += 1
        return next_id[0] - 1

    for step in range(400):
        tokens = rng.integers(0, 5, size=rng.integers(0, 40)).tolist()
        keys = _keys(tokens, bt)
        if rng.random() < 0.5:
            tree.insert(keys, alloc)
            ref.insert(keys)
        else:
            ids, _node = tree.match(keys)
            assert len(ids) == ref.match_len(keys), (step, tokens)
        tree.check()


def test_radix_match_returns_blocks_in_insert_order():
    tree = RadixTree()
    keys = [(1, 2), (3, 4), (5, 6)]
    tree.insert(keys, lambda j: 10 + j)
    ids, node = tree.match(keys)
    assert ids == [10, 11, 12]
    # partial lookup stops mid-edge, no split needed
    ids2, _ = tree.match(keys[:2])
    assert ids2 == [10, 11]
    # divergent insert splits; shared blocks keep their identity
    keys_b = [(1, 2), (3, 4), (7, 8)]
    tree.insert(keys_b, lambda j: 20 + j)
    ids3, _ = tree.match(keys_b)
    assert ids3 == [10, 11, 22]
    tree.check()


def test_radix_eviction_respects_leases_and_lru():
    tree = RadixTree()
    tree.insert([(1,), (2,)], lambda j: j)          # blocks 0, 1
    tree.insert([(1,), (9,)], lambda j: 10 + j)     # splits; block 11
    # pin the (9,) leaf via a match lease
    ids, node = tree.match([(1,), (9,)])
    tree.acquire(node)
    # LRU order now favors the (2,) leaf; the pinned leaf must survive
    # even when evict is called repeatedly
    freed = tree.evict_lru_leaf()
    assert freed == [1]                              # the (2,) tail
    assert tree.evict_lru_leaf() == []               # (9,) pinned, (1,)
    tree.check()                                     # has a child
    tree.release(node)
    freed2 = tree.evict_lru_leaf()
    assert 11 in freed2                              # now evictable
    tree.check()


def test_radix_release_without_acquire_raises():
    tree = RadixTree()
    tree.insert([(1,)], lambda j: j)
    _, node = tree.match([(1,)])
    with pytest.raises(RuntimeError, match="release"):
        tree.release(node)


# ---------------------------------------------------------------------------
# pool accounting


def test_pool_alloc_free_accounting_balances():
    pool = KVBlockPool(4, num_layers=2, num_kv_heads=2, block_tokens=2,
                       head_dim=3, dtype=np.float32)
    assert pool.resident_bytes == 0
    ids = [pool.alloc() for _ in range(4)]
    assert pool.alloc() is None                      # exhausted
    assert pool.used_blocks == 4
    assert pool.resident_bytes == pool.capacity_bytes
    pool.free(ids)
    assert pool.free_blocks == 4 and pool.resident_bytes == 0
    with pytest.raises(ValueError):
        pool.free([99])


def test_pool_gather_roundtrips_block_data():
    pool = KVBlockPool(3, num_layers=1, num_kv_heads=2, block_tokens=2,
                       head_dim=4, dtype=np.float32)
    rng = np.random.default_rng(1)
    a, b = pool.alloc(), pool.alloc()
    ka = rng.normal(size=(1, 2, 2, 4)).astype(np.float32)
    kb = rng.normal(size=(1, 2, 2, 4)).astype(np.float32)
    pool.write(a, ka, ka + 1)
    pool.write(b, kb, kb + 1)
    k, v = pool.gather([a, b])
    assert k.shape == (1, 2, 4, 4)                   # [L, H, n*bt, D]
    np.testing.assert_array_equal(k[:, :, :2], ka)
    np.testing.assert_array_equal(k[:, :, 2:], kb)
    np.testing.assert_array_equal(v[:, :, 2:], kb + 1)


# ---------------------------------------------------------------------------
# manager: lease/CoW/eviction invariants (host-only; numpy "device" rows)


def _mgr(num_blocks=8, bt=4, L=2, H=2, D=4):
    return KVCacheManager(L, H, D, num_blocks=num_blocks,
                          block_tokens=bt, dtype=np.float32)


def _row(rng, L=2, H=2, D=4, S=64):
    return (rng.normal(size=(L, 1, H, S, D)).astype(np.float32),
            rng.normal(size=(L, 1, H, S, D)).astype(np.float32))


def test_manager_match_caps_below_prompt_and_roundtrips_data():
    rng = np.random.default_rng(2)
    mgr = _mgr()
    k, v = _row(rng)
    prompt = np.arange(12)                           # 3 whole blocks
    assert mgr.match(prompt) is None                 # cold: miss
    mgr.store(prompt, k, v)
    lease = mgr.match(prompt)                        # exact repeat
    assert lease.tokens == 8                         # capped below plen
    pk, pv = lease.gather()
    np.testing.assert_array_equal(pk, k[:, 0, :, :8])
    np.testing.assert_array_equal(pv, v[:, 0, :, :8])
    lease.release()
    longer = np.concatenate([np.arange(12), [7, 7, 7, 7, 7]])
    lease2 = mgr.match(longer)                       # mid-prompt hit
    assert lease2.tokens == 12
    lease2.release()
    assert mgr.peek(longer) == 12                    # peek = match, no stats
    assert mgr.stats["hits"] == 2 and mgr.stats["misses"] == 1


def test_manager_store_skips_existing_blocks():
    rng = np.random.default_rng(3)
    mgr = _mgr()
    k, v = _row(rng)
    mgr.store(np.arange(8), k, v)                    # 2 blocks
    added = mgr.store(np.concatenate([np.arange(8), [50, 51, 52, 53]]),
                      k, v)
    assert added == 1                                # only the new tail
    assert mgr.snapshot()["blocks_used"] == 3


def test_manager_eviction_honors_live_leases():
    """ISSUE 3 acceptance: eviction honors live leases — a pinned match
    survives arbitrary pool pressure and still gathers the exact bytes
    it matched; releasing makes it reclaimable."""
    rng = np.random.default_rng(4)
    mgr = _mgr(num_blocks=4, bt=4)
    k, v = _row(rng)
    prompt = np.arange(8)                            # 2 blocks
    mgr.store(prompt, k, v)
    lease = mgr.match(np.concatenate([prompt, [9]]))
    assert lease.tokens == 8
    # flood the pool: every new store needs blocks the leased entry holds
    for i in range(6):
        nk, nv = _row(rng)
        mgr.store(rng.integers(100, 200, size=12), nk, nv)
        snap = mgr.snapshot()
        assert snap["blocks_used"] <= 4
    # the leased blocks were never reclaimed: the gather still matches
    pk, pv = lease.gather()
    np.testing.assert_array_equal(pk, k[:, 0, :, :8])
    lease.release()
    # released: pressure can now reclaim them
    for i in range(4):
        mgr.store(rng.integers(200, 300, size=16), *_row(rng))
    assert mgr.peek(np.concatenate([prompt, [9]])) in (0, 4, 8)


def test_manager_accounting_balances_to_zero_after_drain():
    """Byte accounting: evicting everything returns every block to the
    pool and resident bytes to exactly zero."""
    rng = np.random.default_rng(5)
    mgr = _mgr(num_blocks=8, bt=4)
    for _ in range(5):
        mgr.store(rng.integers(0, 50, size=rng.integers(4, 20)),
                  *_row(rng))
        mgr.tree.check()
    # drain: evict until nothing is left (no leases outstanding)
    while True:
        freed = mgr.tree.evict_lru_leaf()
        if not freed:
            break
        mgr.pool.free(freed)
    snap = mgr.snapshot()
    assert snap["blocks_used"] == 0
    assert snap["resident_bytes"] == 0
    assert snap["nodes"] == 0
    assert mgr.pool.free_blocks == mgr.pool.num_blocks
    mgr.tree.check()


def test_manager_random_workload_invariants():
    """Property sweep over random match/store/evict interleavings with
    live leases: the pool never over-commits, leased gathers always
    return the bytes that were stored, accounting never drifts."""
    rng = np.random.default_rng(6)
    mgr = _mgr(num_blocks=6, bt=2)
    stored = {}                                      # tuple(prompt) -> row
    leases = []
    for step in range(300):
        op = rng.random()
        prompt = rng.integers(0, 4, size=rng.integers(2, 14))
        if op < 0.45:
            k, v = _row(rng)
            mgr.store(prompt, k, v)
            stored[tuple(int(t) for t in prompt)] = (k, v)
        elif op < 0.8:
            lease = mgr.match(prompt)
            if lease is not None and len(leases) < 3:
                leases.append(lease)
            elif lease is not None:
                lease.release()
        elif leases:
            leases.pop(rng.integers(len(leases))).release()
        mgr.tree.check()
        snap = mgr.snapshot()
        assert snap["blocks_used"] <= 6
        assert (snap["blocks_used"] * mgr.pool.block_bytes
                == snap["resident_bytes"])
        assert mgr.pool.free_blocks + snap["blocks_used"] == 6
    for lease in leases:
        lease.release()


def test_env_knobs_and_byte_budget(monkeypatch):
    from distributed_inference_demo_tpu.runtime.kvcache import (
        resolve_kvcache_config)
    monkeypatch.setenv("DWT_KVCACHE_BLOCKS", "12")
    monkeypatch.setenv("DWT_KVCACHE_BLOCK_TOKENS", "8")
    assert resolve_kvcache_config(None, None) == (12, 8)
    assert resolve_kvcache_config(3, 2) == (3, 2)    # explicit wins
    monkeypatch.delenv("DWT_KVCACHE_BLOCKS")
    assert resolve_kvcache_config(None, 4, default_blocks=64) == (64, 4)
    # DWT_KVCACHE_BYTES shrinks the pool to fit
    mgr_free = _mgr(num_blocks=8, bt=4)
    monkeypatch.setenv("DWT_KVCACHE_BYTES",
                       str(3 * mgr_free.pool.block_bytes))
    mgr_capped = _mgr(num_blocks=8, bt=4)
    assert mgr_capped.pool.num_blocks == 3
    # a ceiling below ONE block disables the cache (for_model -> None)
    # instead of crashing engine construction — the knob is a ceiling
    import types
    cfg = types.SimpleNamespace(num_layers=2, num_kv_heads=2, head_dim=4,
                                dtype=np.float32)
    monkeypatch.setenv("DWT_KVCACHE_BYTES", "1")
    assert KVCacheManager.for_model(cfg, 8, 4) is None
    monkeypatch.delenv("DWT_KVCACHE_BYTES")
    assert KVCacheManager.for_model(cfg, 8, 4) is not None


# ---------------------------------------------------------------------------
# engine exactness: cold vs primed token identity (ISSUE 3 acceptance)


@pytest.fixture(scope="module")
def tiny():
    import jax
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import (
        init_full_params)
    cfg = get_model_config("llama-test")
    return cfg, init_full_params(jax.random.PRNGKey(0), cfg)


GREEDY_KW = {}


def _greedy():
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    return SamplingParams(greedy=True)


# the unchunked variant is a redundant-coverage twin of
# tests/test_kv_backend.py's plain-engine layout-parity test (which
# runs cold + primed on the same path); the chunked variant is the
# unique coverage and stays in the fast lane
@pytest.mark.parametrize("chunk", [
    pytest.param(None, marks=pytest.mark.slow), 8])
def test_engine_primed_vs_cold_exactness(tiny, chunk):
    """InferenceEngine path: generating the same prompt (shared prefix +
    fresh suffix) on a COLD engine and on one PRIMED with the prefix is
    token-identical under greedy sampling, blocking and streaming."""
    from distributed_inference_demo_tpu.runtime import InferenceEngine
    cfg, params = tiny
    cold = InferenceEngine(cfg, params, max_seq=96, sampling=_greedy(),
                           prefill_chunk=chunk)
    primed = InferenceEngine(cfg, params, max_seq=96, sampling=_greedy(),
                             prefill_chunk=chunk, kv_cache_blocks=32,
                             kv_block_tokens=4)
    shared = list(range(2, 22))                     # 20 tokens = 5 blocks
    prompt = np.asarray([shared + [51, 52, 53]])
    primed.generate(np.asarray([shared + [90]]), 4)  # prime the cache
    want = cold.generate(prompt, 10).tokens
    got = primed.generate(prompt, 10).tokens
    np.testing.assert_array_equal(got, want)
    assert primed.kv_cache.stats["hits"] == 1
    assert primed.kv_cache.stats["partial_hit_tokens"] == 20
    # streaming twin
    streamed = np.concatenate(
        list(primed.generate_stream(prompt, 10)))
    np.testing.assert_array_equal(streamed, want[0])


def test_engine_near_capacity_suffix_single_dispatch(tiny):
    """The cap<C seeded-suffix branch of run_chunked_prefill: a prefix
    hit within one chunk of max_seq still decodes exactly."""
    from distributed_inference_demo_tpu.runtime import InferenceEngine
    cfg, params = tiny
    cold = InferenceEngine(cfg, params, max_seq=32, sampling=_greedy(),
                           prefill_chunk=8)
    primed = InferenceEngine(cfg, params, max_seq=32, sampling=_greedy(),
                             prefill_chunk=8, kv_cache_blocks=32,
                             kv_block_tokens=4)
    base = list(range(1, 29))                       # 28 tokens
    prompt = np.asarray([base[:28] + [3, 4]])       # 30 tokens, suffix 2
    primed.generate(np.asarray([base]), 2)
    want = cold.generate(prompt, 2).tokens
    got = primed.generate(prompt, 2).tokens
    np.testing.assert_array_equal(got, want)
    assert primed.kv_cache.stats["hits"] == 1
    assert primed.kv_cache.stats["partial_hit_tokens"] == 28


@pytest.mark.slow
def test_speculative_target_primed_vs_cold_exactness(tiny):
    """SpeculativeEngine path: target-side block reuse keeps greedy
    output bit-identical to the cold plain engine.  Slow lane: the
    quick lane keeps two spec-pool reps — test_kv_backend's
    page-sharing ownership test (primed == cold equality) and
    test_kv_quant's speculative cold-oracle/primed-floor test."""
    import jax
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.decoder import (
        init_full_params)
    from distributed_inference_demo_tpu.runtime import (InferenceEngine,
                                                        SpeculativeEngine)
    cfg, params = tiny
    dcfg = get_model_config("llama-test-int8")
    dparams = init_full_params(jax.random.PRNGKey(0), dcfg, quantize=True)
    cold = InferenceEngine(cfg, params, max_seq=96, sampling=_greedy())
    spec = SpeculativeEngine(cfg, params, dcfg, dparams, max_seq=96,
                             sampling=_greedy(), num_draft=3,
                             kv_cache_blocks=32, kv_block_tokens=4)
    shared = list(range(3, 23))                     # 20 tokens
    prompt = np.asarray([shared + [61, 62, 63]])
    spec.generate(np.asarray([shared + [90]]), 4)   # prime (target side)
    want = cold.generate(prompt, 10).tokens
    got, _stats = spec.generate(prompt, 10)
    np.testing.assert_array_equal(got.tokens, want)
    assert spec.kv_cache.stats["hits"] == 1


def test_engine_scrape_and_debugz_fragments(tiny):
    """The plain engine exposes its cache on /metrics (scrape_stats) and
    /debugz (debug_state) without growing a /stats surface."""
    from distributed_inference_demo_tpu.runtime import InferenceEngine
    from distributed_inference_demo_tpu.telemetry import catalog
    cfg, params = tiny
    eng = InferenceEngine(cfg, params, max_seq=64, sampling=_greedy(),
                          kv_cache_blocks=8, kv_block_tokens=4)
    prompt = np.asarray([list(range(1, 13))])
    eng.generate(prompt, 4)
    eng.generate(prompt, 4)
    assert eng.kv_cache.stats["hits"] == 1
    text = catalog.scrape(eng)
    assert "dwt_kvcache_hits_total 1" in text
    # the deprecated dwt_batching_prefix_* aliases are REMOVED (PR 3
    # kept them one release; tools/check_metrics_names.py guards the
    # tombstone)
    assert "dwt_batching_prefix_cache_hits_total" not in text
    dbg = eng.debug_state()["kvcache"]
    assert dbg["blocks_used"] > 0 and "lru_leaves" in dbg
    assert not hasattr(eng, "stats")
