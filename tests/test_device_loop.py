"""Device-resident K-token decode loop (docs/DESIGN.md §13).

Acceptance invariants pinned here:

- greedy output is BIT-IDENTICAL between the per-token path (K=1) and
  the device loop at every K — including mid-block eos and on-device
  stop-token cuts — for the streaming engine, the dense and paged fused
  batching blocks (their parity lives in test_batching/test_paged_
  batching; the early-exit accounting lives here), and the ring
  pipeline's fused tail;
- host dispatches per token ≈ 1/K on the streaming path (the
  BENCH_SELF_r05 15.31 ms dispatch floor amortizes K-fold);
- an all-rows-done at step j < K ends the device loop after j steps —
  the remaining K−j steps are NOT executed (the device-reported step
  count proves it).
"""

import threading

import jax
import numpy as np
import pytest

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import (
    SamplingParams, match_stop_ids, pad_stop_ids)
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


def make_engine(params, **kw):
    kw.setdefault("sampling", GREEDY)
    return InferenceEngine(CFG, params, max_seq=96, **kw)


def stream_tokens(engine, prompt, n, seed=0, logprobs=False):
    return list(engine.generate_stream(prompt, n, seed=seed,
                                       logprobs=logprobs))


PROMPT = np.asarray([[3, 14, 15, 92, 65], [7, 6, 5, 4, 3]], np.int32)


# ---------------------------------------------------------------- parity

@pytest.mark.parametrize("K", [
    4, pytest.param(16, marks=pytest.mark.slow)])
def test_stream_block_greedy_bit_identical(params, K):
    ref = stream_tokens(make_engine(params, stream_block=1), PROMPT, 24)
    got = stream_tokens(make_engine(params, stream_block=K), PROMPT, 24)
    assert len(got) == len(ref)
    np.testing.assert_array_equal(np.stack(ref, 1), np.stack(got, 1))


def test_stream_block_logprobs_bit_identical(params):
    ref = stream_tokens(make_engine(params, stream_block=1), PROMPT, 12,
                        logprobs=True)
    got = stream_tokens(make_engine(params, stream_block=8), PROMPT, 12,
                        logprobs=True)
    assert len(got) == len(ref)
    for (rt, rl), (gt, gl) in zip(ref, got):
        np.testing.assert_array_equal(rt, gt)
        np.testing.assert_array_equal(rl, gl)


# slow lane: sampled-stream twin — the rng-stream claim is pinned quick by
# test_mixed_sampled_stream_bit_identical_to_serialized (mixed dispatch)
@pytest.mark.slow
def test_stream_block_sampled_bit_identical(params):
    """K-fusion must not perturb the rng stream: the loop body splits
    the carried rng per step in decode_one's exact order, so SAMPLED
    streams (not just greedy) are bit-identical across K."""
    samp = SamplingParams(temperature=0.8, top_k=5)
    ref = stream_tokens(make_engine(params, sampling=samp,
                                    stream_block=1), PROMPT, 16, seed=11)
    got = stream_tokens(make_engine(params, sampling=samp,
                                    stream_block=4), PROMPT, 16, seed=11)
    np.testing.assert_array_equal(np.stack(ref, 1), np.stack(got, 1))


def test_generate_matches_stream_any_block(params):
    """The fused ``generate`` path runs the same device loop (one block
    of size max_new): parity with the streamed per-token path."""
    eng = make_engine(params, stream_block=1)
    fused = eng.generate(PROMPT, 10).tokens
    streamed = np.stack(stream_tokens(eng, PROMPT, 10), 1)
    np.testing.assert_array_equal(fused, streamed)


# ------------------------------------------------- dispatch accounting

@pytest.mark.quick
def test_dispatches_per_token_is_one_over_K(params):
    """THE headline invariant: with stream_block=K the host pays one
    dispatch per K tokens; K=1 pays one per token."""
    for K, want_dispatches in ((1, 16), (4, 4), (16, 1)):
        eng = make_engine(params, stream_block=K)
        toks = stream_tokens(eng, PROMPT, 16)
        assert len(toks) == 16
        # prefill is not a decode dispatch; only the loop counts
        assert eng.loop_stats["host_dispatches"] == want_dispatches, K
        assert eng.loop_stats["device_loop_steps"] == 16, K
        ratio = eng.loop_stats["host_dispatches"] / len(toks)
        assert abs(ratio - 1 / K) < 1e-9


def test_dwt_engine_series_feed(params):
    """The instance counters bridge to the dwt_engine_* catalog series
    (scraped dispatches-per-token is the §13 runbook signal)."""
    from distributed_inference_demo_tpu.telemetry.catalog import (
        ENGINE_DEVICE_LOOP_STEPS, ENGINE_HOST_DISPATCHES)

    def val(counter):
        return {key: v for _, key, v in counter.samples()}.get(
            ((("engine", "InferenceEngine"),)), 0.0)

    d0, s0 = val(ENGINE_HOST_DISPATCHES), val(ENGINE_DEVICE_LOOP_STEPS)
    eng = make_engine(params, stream_block=4)
    stream_tokens(eng, PROMPT, 8)
    assert val(ENGINE_HOST_DISPATCHES) - d0 == 2
    assert val(ENGINE_DEVICE_LOOP_STEPS) - s0 == 8


# ------------------------------------------------------ early exit

def _nth_greedy_token(params, n, prompt=None):
    """Token the greedy reference emits at step index n (row 0)."""
    toks = stream_tokens(make_engine(params),
                         PROMPT[:1] if prompt is None else prompt, n + 1)
    return int(toks[n][0])


@pytest.mark.slow
def test_all_rows_eos_ends_device_loop_early(params):
    """All-rows-EOS at step j < K must end the loop after j+1 steps —
    the remaining K−(j+1) steps are NOT run (device-reported count)."""
    eos = _nth_greedy_token(params, 2)
    eng = make_engine(params, stream_block=16)
    eng.eos_id = eos
    toks = stream_tokens(eng, PROMPT[:1], 12)
    assert len(toks) == 3 and int(toks[-1][0]) == eos
    assert eng.loop_stats["host_dispatches"] == 1
    assert eng.loop_stats["device_loop_steps"] == 3    # not 12, not 16
    # K=1 reference: same tokens, one dispatch each
    ref_eng = make_engine(params, stream_block=1)
    ref_eng.eos_id = eos
    ref = stream_tokens(ref_eng, PROMPT[:1], 12)
    np.testing.assert_array_equal(np.stack(ref, 1), np.stack(toks, 1))
    assert ref_eng.loop_stats["host_dispatches"] == 3


def test_fused_generate_early_exits_on_eos(params):
    """The non-streaming ``generate`` block exits at the eos step too
    (the old fixed-trip scan burned the full block), while its output
    keeps the deterministic eos padding contract."""
    eos = _nth_greedy_token(params, 2)
    eng = make_engine(params)
    eng.eos_id = eos
    res = eng.generate(PROMPT[:1], 10)
    assert res.tokens.shape == (1, 10)
    assert (res.tokens[0, 3:] == eos).all()
    assert eng.loop_stats["host_dispatches"] == 1
    assert eng.loop_stats["device_loop_steps"] == 3


# ------------------------------------------------- on-device stop ids

@pytest.mark.slow
def test_stop_token_ids_cut_matches_per_token_path(params):
    stop_tok = _nth_greedy_token(params, 3)
    outs = {}
    for K in (1, 8):
        eng = make_engine(params, stream_block=K,
                          stop_token_ids=[stop_tok, 9999])
        outs[K] = stream_tokens(eng, PROMPT[:1], 12)
        # the stop token is emitted (eos-include convention), then the
        # row is done: the stream ends at the cut on both paths
        assert len(outs[K]) == 4
        assert int(outs[K][-1][0]) == stop_tok
    np.testing.assert_array_equal(np.stack(outs[1], 1),
                                  np.stack(outs[8], 1))


def test_stop_token_ids_early_exit_accounting(params):
    stop_tok = _nth_greedy_token(params, 1)
    eng = make_engine(params, stream_block=16,
                      stop_token_ids=[stop_tok])
    toks = stream_tokens(eng, PROMPT[:1], 12)
    assert len(toks) == 2
    assert eng.loop_stats == {"host_dispatches": 1,
                              "device_loop_steps": 2}


def test_stop_id_helpers():
    np.testing.assert_array_equal(np.asarray(pad_stop_ids(None)), [-1])
    np.testing.assert_array_equal(np.asarray(pad_stop_ids([7, 3, 7])),
                                  [3, 7])
    with pytest.raises(ValueError, match="stop_token_ids"):
        pad_stop_ids([-2])
    import jax.numpy as jnp
    got = match_stop_ids(jnp.asarray([3, 7, 5]), pad_stop_ids([3, 5]))
    np.testing.assert_array_equal(np.asarray(got), [True, False, True])
    # the empty sentinel can never match a real (non-negative) token
    got = match_stop_ids(jnp.asarray([0, 1]), pad_stop_ids(None))
    assert not np.asarray(got).any()


def test_stream_block_validation(params):
    with pytest.raises(ValueError, match="stream_block"):
        make_engine(params, stream_block=0)


def test_stream_block_env_knob(params, monkeypatch):
    monkeypatch.setenv("DWT_STREAM_BLOCK", "4")
    eng = make_engine(params)           # stream_block=None -> env
    assert eng.stream_block == 4
    stream_tokens(eng, PROMPT[:1], 8)
    assert eng.loop_stats["host_dispatches"] == 2


# ------------------------------------- batching fused-block early exit

def test_batching_fused_block_reports_actual_steps(params):
    """The dense fused block's on-device active count: a block whose
    rows all exhaust their budget at step j < decode_block runs j
    steps, and the drain sees the device-reported count."""
    oracle = make_engine(params)
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  decode_block=16) as eng:
        got = eng.submit([3, 14, 15, 92, 65], 5).wait(timeout=300)
        want = oracle.generate(np.asarray([[3, 14, 15, 92, 65]]),
                               5).tokens[0]
        np.testing.assert_array_equal(got, want)
        stats = eng.loop_stats.copy()
    # token #1 comes from prefill; the 4 decode tokens need at most ONE
    # 16-step fused block that early-exits on the budget — without the
    # exit the block would burn 16 steps into stale positions
    assert stats["device_loop_steps"] < 16
    assert stats["device_loop_steps"] >= 4


@pytest.mark.slow
def test_paged_fused_block_reports_actual_steps(params):
    oracle = make_engine(params)
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  decode_block=16,
                                  kv_layout="paged") as eng:
        got = eng.submit([3, 14, 15, 92, 65], 5).wait(timeout=300)
        want = oracle.generate(np.asarray([[3, 14, 15, 92, 65]]),
                               5).tokens[0]
        np.testing.assert_array_equal(got, want)
        stats = eng.loop_stats.copy()
    assert stats["device_loop_steps"] < 16
    assert stats["device_loop_steps"] >= 4


# slow lane: eos-mid-block twin; test_fused_generate_early_exits_on_eos,
# test_stop_token_ids_early_exit_accounting and the batching-level
# test_decode_block_eos_mid_block keep the seam quick
@pytest.mark.slow
def test_batching_eos_mid_block_early_exit(params):
    """An all-rows-EOS inside the fused block ends it on device: parity
    plus the step count proves the remaining rounds never ran."""
    oracle = make_engine(params)
    prompt = [3, 14, 15, 92, 65]
    ref = oracle.generate(np.asarray([prompt]), 8).tokens[0]
    eos = int(ref[2])
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  decode_block=16, eos_id=eos) as eng:
        got = eng.submit(prompt, 30).wait(timeout=300)
        stats = eng.loop_stats.copy()
    np.testing.assert_array_equal(got, ref[:list(ref).index(eos) + 1])
    assert stats["device_loop_steps"] < 30


# ----------------------------------------------------- ring fused tail

def _run_ring(model, fused: bool, monkeypatch):
    from tests.test_distributed import PROMPT as RING_PROMPT
    from tests.test_distributed import build_pipeline
    monkeypatch.setenv("DWT_RING_FUSED_TAIL", "1" if fused else "0")
    header, threads = build_pipeline(model, 2)
    try:
        toks = header.generate(RING_PROMPT, 10)
    finally:
        header.shutdown_pipeline()
        for t in threads:
            t.join(timeout=30)
    return toks


# tier-1 budget: dispatches-per-token + stream-block parity keep the
# quick-lane fused-loop reps; the ring-mesh tail twin rides slow
@pytest.mark.slow
def test_ring_fused_tail_parity(params, monkeypatch):
    """The tail's fused forward+sample program must emit bit-identical
    tokens to the split forward-then-sample pair it replaces (same rng
    fold_in stream by construction; this pins it)."""
    split = _run_ring("llama-test", False, monkeypatch)
    fused = _run_ring("llama-test", True, monkeypatch)
    np.testing.assert_array_equal(split, fused)


@pytest.mark.slow
def test_ring_fused_tail_halves_tail_dispatches(monkeypatch):
    """Tail dispatch accounting: the fused tail pays 1 host dispatch
    per token where the split pair paid 2."""
    from distributed_inference_demo_tpu.comm.transport import (
        LoopbackNetwork, LoopbackTransport)
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.base import (
        slice_stage, split_layer_ranges)
    from distributed_inference_demo_tpu.runtime.distributed import (
        PipelineHeader, PipelineWorker, StageRuntime)

    counts = {}
    for fused in (False, True):
        monkeypatch.setenv("DWT_RING_FUSED_TAIL", "1" if fused else "0")
        cfg = get_model_config("llama-test")
        full = init_full_params(jax.random.PRNGKey(0), cfg)
        specs = split_layer_ranges(cfg.num_layers, 2)
        net = LoopbackNetwork()
        t0, t1 = (LoopbackTransport(d, net) for d in ("s0", "s1"))
        header = PipelineHeader(
            StageRuntime(cfg, specs[0],
                         slice_stage(full, cfg, specs[0]), 64, GREEDY),
            t0, next_id="s1", step_timeout=60)
        worker = PipelineWorker(
            StageRuntime(cfg, specs[1],
                         slice_stage(full, cfg, specs[1]), 64, GREEDY),
            t1, next_id=None, header_id="s0", step_timeout=60)
        th = threading.Thread(target=worker.serve_forever, daemon=True)
        th.start()
        try:
            header.generate(np.asarray([[5, 17, 42, 7]], np.int32), 8)
        finally:
            header.shutdown_pipeline()
            th.join(timeout=30)
        counts[fused] = worker.tail_dispatches
    assert counts[True] * 2 == counts[False]
    assert counts[True] > 0


def test_cli_stream_block_mode_rules(capsys):
    """--stream-block is honored by the plain engine path and REJECTED
    (never silently ignored) by modes with their own fusion unit."""
    from distributed_inference_demo_tpu import cli
    assert cli.main(["serve", "--model", "llama-test",
                     "--batch-slots", "2", "--stream-block", "4"]) == 1
    assert "--stream-block" in capsys.readouterr().err
