"""Postmortem bundles end to end: the writer's bundle format, the crash
handler, the offline analyzer (``tools/postmortem.py``), and the ISSUE-2
acceptance scenario — a worker killed mid-ring produces a bundle whose
analysis names the correct offending hop."""

import importlib.util
import json
import os
import pathlib
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport, TransportTimeout)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import (
    slice_stage, split_layer_ranges)
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime.distributed import (
    PipelineHeader, PipelineWorker, StageRuntime)
from distributed_inference_demo_tpu.telemetry import postmortem
from distributed_inference_demo_tpu.telemetry.flightrecorder import (
    FlightRecorder, get_flight_recorder, set_flight_recorder)
from distributed_inference_demo_tpu.telemetry.postmortem import (
    PostmortemWriter)

REPO = pathlib.Path(__file__).resolve().parents[1]
GREEDY = SamplingParams(greedy=True)
PROMPT = np.array([[5, 17, 42, 7, 99, 3, 12, 56]], dtype=np.int32)


def _load_analyzer():
    spec = importlib.util.spec_from_file_location(
        "postmortem_tool", REPO / "tools" / "postmortem.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _isolate_globals():
    set_flight_recorder(None)
    postmortem.set_postmortem_writer(None)
    yield
    set_flight_recorder(None)
    postmortem.set_postmortem_writer(None)


# ---------------------------------------------------------------------------
# writer unit behavior


def test_bundle_contains_all_pieces(tmp_path):
    fr = get_flight_recorder()
    fr.record("hop_send", stage="h", rid=0, step=0, dest="w1")
    fr.record("anomaly", anomaly="slo_ttft", severity="critical")
    w = PostmortemWriter(str(tmp_path))
    path = w.write_bundle("slo_ttft", detail={"why": "test"},
                          config={"model": "llama-test"},
                          spans=[{"name": "compute", "proc": "h",
                                  "trace_id": 1, "span_id": 2,
                                  "ts_us": 1000, "dur_us": 500}])
    p = pathlib.Path(path)
    manifest = json.loads((p / "manifest.json").read_text())
    assert manifest["reason"] == "slo_ttft"
    assert manifest["detail"] == {"why": "test"}
    assert manifest["flight_events"] == 2
    flight = [json.loads(l) for l in
              (p / "flight.jsonl").read_text().splitlines()]
    assert [e["kind"] for e in flight] == ["hop_send", "anomaly"]
    assert "dwt_flight_events_total" in (p / "metrics.prom").read_text()
    trace = json.loads((p / "trace.json").read_text())
    phs = {e["ph"] for e in trace["traceEvents"]}
    assert "X" in phs and "i" in phs      # spans + flight instants
    assert json.loads((p / "config.json").read_text())["model"] == \
        "llama-test"


def test_bundle_captures_runlog_tail(tmp_path):
    from distributed_inference_demo_tpu.telemetry.runlog import (
        RunLog, set_run_log)
    log_path = tmp_path / "run.jsonl"
    rl = RunLog(str(log_path))
    set_run_log(rl)
    try:
        rl.event("serve_start", model="llama-test")
        rl.event("generate", batch=1)
        w = PostmortemWriter(str(tmp_path / "pm"))
        path = w.write_bundle("crash")
        tail = (pathlib.Path(path) / "runlog_tail.jsonl").read_text()
        events = [json.loads(l) for l in tail.splitlines()]
        assert [e["event"] for e in events] == ["serve_start", "generate"]
    finally:
        set_run_log(None)
        rl.close()


def test_bundles_pruned_to_max(tmp_path):
    w = PostmortemWriter(str(tmp_path), max_bundles=2)
    for i in range(5):
        w.write_bundle(f"r{i}")
    dirs = w.bundle_dirs()
    assert len(dirs) == 2
    assert dirs[0].endswith("-r3") and dirs[1].endswith("-r4")


def test_trigger_noop_until_configured(tmp_path, monkeypatch):
    monkeypatch.delenv("DWT_POSTMORTEM_DIR", raising=False)
    assert postmortem.trigger("whatever") is None
    postmortem.set_postmortem_writer(PostmortemWriter(str(tmp_path)))
    assert postmortem.trigger("now_real") is not None
    assert len(list(tmp_path.glob("pm-*"))) == 1


def test_trigger_honors_env_dir(tmp_path, monkeypatch):
    monkeypatch.setenv("DWT_POSTMORTEM_DIR", str(tmp_path / "boxes"))
    postmortem.set_postmortem_writer(None)     # re-resolve lazily
    assert postmortem.trigger("env_configured") is not None
    assert len(list((tmp_path / "boxes").glob("pm-*"))) == 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_crash_handler_dumps_bundle_from_thread(tmp_path):
    postmortem.set_postmortem_writer(PostmortemWriter(str(tmp_path)))
    postmortem.install_crash_handler(config={"who": "test"})

    def boom():
        raise RuntimeError("device fell over")

    t = threading.Thread(target=boom)
    t.start()
    t.join()
    bundles = list(tmp_path.glob("pm-*"))
    assert len(bundles) == 1
    manifest = json.loads((bundles[0] / "manifest.json").read_text())
    assert manifest["reason"] == "crash"
    assert manifest["detail"]["exc_type"] == "RuntimeError"
    assert "device fell over" in manifest["detail"]["exc"]


def test_crash_handler_skips_deliberate_shutdown(tmp_path, capsys):
    """Ctrl-C / sys.exit are shutdowns, not crashes: no bundle (a
    rolling restart must not prune real incident bundles)."""
    postmortem.set_postmortem_writer(PostmortemWriter(str(tmp_path)))
    postmortem.install_crash_handler()
    for exc_type in (KeyboardInterrupt, SystemExit):
        sys.excepthook(exc_type, exc_type(), None)
    assert list(tmp_path.glob("pm-*")) == []
    sys.excepthook(RuntimeError, RuntimeError("real"), None)
    assert len(list(tmp_path.glob("pm-*"))) == 1
    capsys.readouterr()          # swallow the chained hook's traceback


def test_bundle_names_carry_pid(tmp_path):
    """Processes share DWT_POSTMORTEM_DIR in a ring deployment; the pid
    in the directory name keeps same-second bundles from overwriting
    each other."""
    w = PostmortemWriter(str(tmp_path))
    path = w.write_bundle("crash")
    assert f"-p{os.getpid()}-" in pathlib.Path(path).name


# ---------------------------------------------------------------------------
# offline analyzer


def test_analyzer_on_golden_bundle_names_the_hop():
    tool = _load_analyzer()
    s = tool.summarize_bundle(str(REPO / "tests" / "data"
                                  / "golden_bundle"))
    assert s["reason"] == "pipeline_stall"
    assert s["offending_hop"] == "w1->w2"
    [d] = s["stalled"]
    assert (d["rid"], d["step"]) == (0, 3)
    assert "never processed" in d["diagnosis"]
    assert s["metrics"].get(
        'dwt_anomaly_events_total{kind="pipeline_stall"}') == 1.0
    # the human rendering carries the verdict too
    assert "OFFENDING HOP: w1->w2" in tool.format_summary(s)


def test_analyzer_cli_smoke_golden_bundle():
    """Tier-1 smoke: the CLI runs against the checked-in golden bundle
    and emits the offending hop as JSON (the runbook path in
    docs/DESIGN.md §8)."""
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "postmortem.py"),
         str(REPO / "tests" / "data" / "golden_bundle"), "--json"],
        capture_output=True, text=True, cwd=str(REPO), timeout=120)
    assert out.returncode == 0, out.stderr
    s = json.loads(out.stdout)
    assert s["offending_hop"] == "w1->w2"
    assert s["reason"] == "pipeline_stall"


def test_analyzer_rejects_non_bundle(tmp_path):
    tool = _load_analyzer()
    with pytest.raises(FileNotFoundError):
        tool.summarize_bundle(str(tmp_path))
    assert tool.main([str(tmp_path)]) == 1


def test_analyzer_single_process_capture_is_honest(tmp_path):
    """A header-only bundle (multi-process ring: workers keep their own
    rings) must name the first UNCONFIRMED hop and say the break is at
    or after it — not claim the destination is dead when its ring simply
    isn't in this bundle."""
    tool = _load_analyzer()
    (tmp_path / "manifest.json").write_text(json.dumps(
        {"reason": "pipeline_stall",
         "detail": {"in_flight": [[1, 0]]}}))
    events = [{"ts": 1.0, "kind": "hop_send", "stage": "header",
               "rid": 1, "step": 0, "dest": "w1"}]
    (tmp_path / "flight.jsonl").write_text(
        "\n".join(json.dumps(e) for e in events) + "\n")
    s = tool.summarize_bundle(str(tmp_path))
    assert s["offending_hop"] == "header->w1"
    [d] = s["stalled"]
    assert "at or after this hop" in d["diagnosis"]
    assert "w1" in d["diagnosis"]


def test_analyzer_compute_stall_diagnosis(tmp_path):
    """A hop_recv with no forwarding send pins the stage's compute."""
    tool = _load_analyzer()
    (tmp_path / "manifest.json").write_text(json.dumps(
        {"reason": "pipeline_stall",
         "detail": {"in_flight": [[2, 5]]}}))
    events = [
        {"ts": 1.0, "kind": "hop_send", "stage": "h", "rid": 2,
         "step": 5, "dest": "w1"},
        {"ts": 1.1, "kind": "hop_recv", "stage": "w1", "rid": 2,
         "step": 5},
    ]
    (tmp_path / "flight.jsonl").write_text(
        "\n".join(json.dumps(e) for e in events) + "\n")
    s = tool.summarize_bundle(str(tmp_path))
    assert s["offending_hop"] == "w1 (compute)"


# ---------------------------------------------------------------------------
# the acceptance scenario: killed worker mid-ring -> bundle -> correct hop


# tier-1 budget: the golden-bundle analyzer + crash-handler tests are
# the quick-lane reps; the real killed-worker run rides the slow lane
@pytest.mark.slow
def test_killed_worker_produces_bundle_with_correct_hop(tmp_path):
    """ISSUE 2 acceptance: a 3-stage loopback ring loses its tail
    mid-run; the header's step timeout captures a postmortem bundle and
    ``tools/postmortem.py`` pins the offending hop to s1->s2."""
    set_flight_recorder(FlightRecorder(max_events=512))
    postmortem.set_postmortem_writer(PostmortemWriter(str(tmp_path)))

    cfg = get_model_config("llama-test")
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 3)
    net = LoopbackNetwork()
    ids = ["s0", "s1", "s2"]
    transports = [LoopbackTransport(d, net) for d in ids]
    header = PipelineHeader(
        StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                     64, GREEDY),
        transports[0], next_id="s1", step_timeout=60)
    workers = [
        PipelineWorker(
            StageRuntime(cfg, specs[i], slice_stage(full, cfg, specs[i]),
                         64, GREEDY),
            transports[i],
            next_id=ids[i + 1] if i + 1 < 3 else None,
            header_id="s0", step_timeout=60)
        for i in (1, 2)]
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in workers]
    for t in threads:
        t.start()

    # healthy warmup (compiles everything, proves the ring works)
    toks = header.generate(PROMPT, 2)
    assert toks.shape == (1, 2)

    # kill the tail mid-ring: its serve loop exits on the direct stop
    header.transport.send("s2", "stop", b"")
    threads[1].join(timeout=30)
    assert not threads[1].is_alive()

    header.step_timeout = 2.0                  # fail fast, test-scale
    with pytest.raises(TransportTimeout):
        header.generate(PROMPT, 4)

    bundles = sorted(tmp_path.glob("pm-*"))
    assert len(bundles) == 1                    # one stall, one bundle
    manifest = json.loads((bundles[0] / "manifest.json").read_text())
    assert manifest["reason"] == "pipeline_stall"
    assert manifest["detail"]["stage"] == "s0"
    assert manifest["detail"]["in_flight"], "stalled step not recorded"

    tool = _load_analyzer()
    s = tool.summarize_bundle(str(bundles[0]))
    # s1 received the hidden state, ran its layers, and sent onward to
    # the dead s2 — the analyzer must pin exactly that hop
    assert s["offending_hop"] == "s1->s2"
    [d] = s["stalled"]
    assert d["last_event"]["stage"] == "s1"
    assert d["last_event"]["dest"] == "s2"

    header.transport.send("s1", "stop", b"")
    threads[0].join(timeout=30)
