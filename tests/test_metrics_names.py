"""Tier-1 hook for the metric-name lint (tools/check_metrics_names.py):
the full standard series set (telemetry/catalog) must follow the
``dwt_<subsystem>_<name>_<unit>`` convention with help text on every
metric — a new metric with a bad name fails the suite, not a style
review."""

import importlib.util
import pathlib

import pytest

from distributed_inference_demo_tpu.telemetry import catalog  # noqa: F401
from distributed_inference_demo_tpu.telemetry.metrics import (
    Counter, Gauge, REGISTRY, Registry)


def _load_lint():
    path = (pathlib.Path(__file__).resolve().parents[1] / "tools"
            / "check_metrics_names.py")
    spec = importlib.util.spec_from_file_location("check_metrics_names",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.quick
def test_standard_catalog_is_clean():
    lint = _load_lint()
    problems = lint.check_registry(REGISTRY)
    assert problems == []


def test_required_flight_anomaly_series_registered():
    """The flight-recorder/anomaly series must exist in the standard
    catalog — their absence would read as a healthy quiet system."""
    lint = _load_lint()
    assert lint.check_required(REGISTRY) == []
    names = {m.name for m in REGISTRY.collect()}
    assert "dwt_anomaly_events_total" in names
    assert "dwt_flight_buffer_events" in names


def test_lint_catches_violations():
    """The lint actually fires: a unitless name, a foreign prefix, a
    counter without _total, and a gauge pretending to be a counter all
    produce violations."""
    lint = _load_lint()
    reg = Registry()
    reg.register(Counter("dwt_stage_emitted_tokens_total",
                         "a clean counter"))
    reg.register(Counter("dwt_stage_stuff", "no unit, no total"))
    reg.register(Gauge("foo_bar_seconds", "foreign prefix"))
    reg.register(Gauge("dwt_stage_bad_seconds_total",
                       "gauge claiming _total"))
    problems = lint.check_registry(reg)
    assert not any("dwt_stage_emitted_tokens_total" in p
                   for p in problems)
    assert any("dwt_stage_stuff" in p and "_total" in p
               for p in problems)
    assert any("dwt_stage_stuff" in p and "unit" in p for p in problems)
    assert any("foo_bar_seconds" in p for p in problems)
    assert any("dwt_stage_bad_seconds_total" in p and "reserved"
               in p for p in problems)


def test_lint_requires_help_text():
    """Help text is enforced at construction (MetricError) AND by the
    lint for registries built another way."""
    import pytest

    from distributed_inference_demo_tpu.telemetry.metrics import \
        MetricError
    with pytest.raises(MetricError):
        Counter("dwt_stage_x_bytes_total", "   ")


def test_main_exits_clean():
    lint = _load_lint()
    assert lint.main() == 0


def test_deprecated_prefix_aliases_removed():
    """The dwt_batching_prefix_* aliases (PR 3, 'one release') are gone
    — and the lint guards the tombstone so they can't quietly return."""
    lint = _load_lint()
    names = {m.name for m in REGISTRY.collect()}
    assert not (lint.FORBIDDEN_SERIES & names)
    reg = Registry()
    reg.register(Counter("dwt_batching_prefix_cache_hits_total",
                         "resurrected alias"))
    assert any("registered again" in p for p in lint.check_required(reg))


def _load_kv_lint():
    path = (pathlib.Path(__file__).resolve().parents[1] / "tools"
            / "check_kv_layout.py")
    spec = importlib.util.spec_from_file_location("check_kv_layout", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.quick
def test_kv_layout_dense_removal_stays_deleted():
    """Zero references to the removed dense identifiers anywhere in
    the package (docs/DESIGN.md §14): the escape hatch is deleted and
    this lint keeps the deletion from silently regrowing."""
    kv_lint = _load_kv_lint()
    root = pathlib.Path(__file__).resolve().parents[1]
    assert kv_lint.check_kv_layout_matrix(root) == []
    assert kv_lint.main() == 0


def test_kv_layout_lint_fires_on_a_resurrected_identifier(tmp_path):
    """The lint actually detects a resurrected dense identifier —
    including inside runtime/kvcache/, the shim's former home."""
    kv_lint = _load_kv_lint()
    pkg = tmp_path / "distributed_inference_demo_tpu" / "runtime"
    pkg.mkdir(parents=True)
    (pkg / "new_engine.py").write_text(
        "from .kvcache import " + "require_dense_kv_layout\n")
    former_home = pkg / "kvcache"
    former_home.mkdir()
    (former_home / "__init__.py").write_text(
        "class " + "DenseKVBackend:\n    ...\n")
    problems = kv_lint.check_kv_layout_matrix(tmp_path)
    assert len(problems) == 2
    assert any("new_engine.py" in p for p in problems)
    assert any("kvcache" in p for p in problems)
