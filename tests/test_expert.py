"""Expert parallelism (MoE over the ``ep`` mesh axis).

The GShard-style capacity dispatch (``decoder._moe_mlp_ep``) and its
shard_map entry point (``parallel.expert.make_ep_stage_fn``) must:

- reproduce the dense ``_moe_mlp`` bit-for-tolerance when capacity is
  generous (no token dropped);
- drop exactly the over-capacity tokens (zero MoE contribution) when the
  capacity factor is small — GShard semantics, not an error;
- run the whole mixtral stage (prefill + decode) E-sliced over ``ep``.

Reference analog: per-device module placement (``server.py:893-905``);
the reference itself has no MoE or EP at all (SURVEY.md §2.7).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from distributed_inference_demo_tpu.parallel.compat import shard_map

from distributed_inference_demo_tpu.models import (
    KVCache, StageSpec, get_model_config)
from distributed_inference_demo_tpu.models.decoder import (
    _moe_mlp, _moe_mlp_ep, init_full_params, stage_forward)
from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
from distributed_inference_demo_tpu.parallel.expert import make_ep_stage_fn


def _layer_moe_params(rng, cfg):
    """One layer's MoE weights (no stacked-L axis), float32."""
    E, H, I = cfg.num_experts, cfg.hidden_size, cfg.intermediate_size
    ks = jax.random.split(rng, 4)
    s = H ** -0.5
    return {
        "router": jax.random.normal(ks[0], (H, E), jnp.float32) * s,
        "w_gate": jax.random.normal(ks[1], (E, H, I), jnp.float32) * s,
        "w_up": jax.random.normal(ks[2], (E, H, I), jnp.float32) * s,
        "w_down": jax.random.normal(ks[3], (E, I, H), jnp.float32)
                  * I ** -0.5,
    }


def _run_ep_mlp(cfg, lp, x, mesh):
    specs = {"router": P(), "w_gate": P("ep", None, None),
             "w_up": P("ep", None, None), "w_down": P("ep", None, None)}
    fn = shard_map(
        lambda lp_, x_: _moe_mlp_ep(cfg, lp_, x_, "ep"),
        mesh=mesh, in_specs=(specs, P("ep")), out_specs=P("ep"),
        check_vma=False)
    return fn(lp, x)


def test_ep_dispatch_matches_dense(devices):
    """Generous capacity: all_to_all dispatch == dense batched experts."""
    cfg = get_model_config("mixtral-test").replace(moe_capacity_factor=4.0)
    lp = _layer_moe_params(jax.random.PRNGKey(0), cfg)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 10, cfg.hidden_size),
                          jnp.float32)
    dense = _moe_mlp(cfg, lp, x)
    mesh = make_mesh(MeshConfig(ep=2), devices)
    with mesh:
        ep = _run_ep_mlp(cfg, lp, x, mesh)
    np.testing.assert_allclose(np.asarray(ep), np.asarray(dense),
                               rtol=2e-4, atol=2e-4)


def test_ep_capacity_drop(devices):
    """factor < 1: tokens beyond each expert's capacity get exactly zero
    MoE output (GShard drop), earlier tokens are untouched."""
    cfg = get_model_config("mixtral-test").replace(moe_capacity_factor=0.5)
    lp = _layer_moe_params(jax.random.PRNGKey(0), cfg)
    # force every token onto experts 0 and 1: capacity per expert is
    # C = ceil(T*k/E * 0.5) with T tokens per rank, all landing on 2 of
    # the 4 experts -> tokens with in-rank index >= C are dropped.
    E = cfg.num_experts
    router = jnp.zeros((cfg.hidden_size, E), jnp.float32)
    router = router.at[:, 0].set(1.0).at[:, 1].set(0.5)
    lp = dict(lp, router=router)

    b, s = 2, 8
    # positive activations => positive sum(x) => router logits rank
    # expert0 > expert1 > rest for EVERY token (the router is linear, so a
    # negative-sum token would otherwise flip the ranking)
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(1),
                                  (b, s, cfg.hidden_size), jnp.float32)) + 0.1
    T = (b // 2) * s                       # tokens per rank at ep=2
    C = int(np.ceil(T * cfg.experts_per_token / E * 0.5))
    assert C < T                           # the test must actually drop

    mesh = make_mesh(MeshConfig(ep=2), devices)
    with mesh:
        y = np.asarray(_run_ep_mlp(cfg, lp, x, mesh))
    dense = np.asarray(_moe_mlp(cfg, lp, x))

    y = y.reshape(2, T, -1)                # [rank, token-in-rank, H]
    dense = dense.reshape(2, T, -1)
    np.testing.assert_allclose(y[:, :C], dense[:, :C], rtol=2e-4, atol=2e-4)
    np.testing.assert_array_equal(y[:, C:], np.zeros_like(y[:, C:]))


@pytest.mark.parametrize("quant", [
    False,
    # int8 twin — slow lane: int4 is the odd packed path and stays
    # quick; int8 expert dequant shares its code shape with int4
    pytest.param("int8", marks=pytest.mark.slow),
    "int4",
])
def test_ep_stage_prefill_decode_parity(quant, devices):
    """Whole mixtral stage E-sliced over ep=2: prefill logits match the
    single-device forward; one decode step on the sharded cache works.
    int8 AND packed int4 expert stacks slice over ep (the E axis is
    orthogonal to int4's packed input axis)."""
    name = "mixtral-test" + (f"-{quant}" if quant else "")
    cfg = get_model_config(name).replace(moe_capacity_factor=8.0)
    params = init_full_params(jax.random.PRNGKey(0), cfg, quantize=quant)
    spec = StageSpec(0, 1, 0, cfg.num_layers)
    b, plen = 2, 8
    ids = (jnp.arange(b * plen, dtype=jnp.int32).reshape(b, plen)
           % cfg.vocab_size)
    pos = jnp.broadcast_to(jnp.arange(plen), (b, plen))

    ref, _ = stage_forward(params, cfg, spec, ids,
                           KVCache.create(cfg, cfg.num_layers, b, 32), pos)

    mesh = make_mesh(MeshConfig(ep=2), devices)
    with mesh:
        fn = make_ep_stage_fn(cfg, spec, mesh, params)
        out, cache = fn(params, ids,
                        KVCache.create(cfg, cfg.num_layers, b, 32), pos)
        nxt = jnp.argmax(out[:, -1, :], axis=-1).astype(jnp.int32)[:, None]
        out2, cache = fn(params, nxt, cache, jnp.full((b, 1), plen))
    np.testing.assert_allclose(np.asarray(ref, np.float32),
                               np.asarray(out, np.float32),
                               rtol=3e-4, atol=3e-4)
    assert int(cache.length) == plen + 1
    assert np.isfinite(np.asarray(out2, np.float32)).all()


def test_ep_rejects_bad_configs(devices):
    mesh = make_mesh(MeshConfig(ep=2), devices)
    dense_cfg = get_model_config("llama-test")
    with pytest.raises(ValueError, match="MoE"):
        make_ep_stage_fn(dense_cfg, StageSpec(0, 1, 0, 4), mesh,
                         init_full_params(jax.random.PRNGKey(0), dense_cfg))
    moe_cfg = get_model_config("mixtral-test").replace(num_experts=3)
    with pytest.raises(ValueError, match="divisible"):
        make_ep_stage_fn(moe_cfg, StageSpec(0, 1, 0, 2), mesh,
                         init_full_params(jax.random.PRNGKey(1), moe_cfg))
