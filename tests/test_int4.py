"""Weight-only int4 (ops/quant.QuantizedArray4): packing exactness,
error bounds, storage halving, engine integration, and the composition
rules (pipeline slicing yes, EP yes, tp rejected loudly)."""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import (StageSpec,
                                                        slice_stage)
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.quant import (QuantizedArray4,
                                                      maybe_quantize,
                                                      quantize_array4)
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine

GREEDY = SamplingParams(greedy=True)


def test_pack_unpack_roundtrip_exact_on_grid():
    """Values already on the int4 grid survive quantize->dequantize
    bit-exactly (packing/unpacking is lossless; only rounding loses)."""
    rng = np.random.default_rng(0)
    grid = rng.integers(-7, 8, size=(6, 64, 16)).astype(np.float32)
    scale = 0.25
    w = jnp.asarray(grid * scale)
    qa = quantize_array4(w, group=64)
    np.testing.assert_allclose(np.asarray(qa.dequantize(jnp.float32)),
                               np.asarray(w), rtol=0, atol=1e-6)


def test_quantization_error_bounded_by_half_step():
    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.normal(size=(4, 128, 32)).astype(np.float32))
    qa = quantize_array4(w)
    dq = np.asarray(qa.dequantize(jnp.float32))
    # per-group step = scale; rounding error <= scale/2 everywhere
    step = np.asarray(qa.scale)                      # (4, 2, 1, 32)
    err = np.abs(dq - np.asarray(w)).reshape(4, 2, 64, 32)
    assert (err <= step / 2 + 1e-6).all()


def test_logical_shape_and_storage_halving():
    w = jnp.ones((8, 256, 64), jnp.float32)
    qa = quantize_array4(w)
    assert qa.shape == (8, 256, 64)
    # packed bytes = half the element count; scales add 4/group per wt
    n = 8 * 256 * 64
    assert qa.q.nbytes == n // 2
    assert qa.nbytes / n < 0.57


def test_odd_input_dim_rejected():
    with pytest.raises(ValueError, match="even"):
        quantize_array4(jnp.ones((3, 5, 4)))


def test_registry_int4_suffix():
    cfg = get_model_config("llama-test-int4")
    assert cfg.quantization == "int4"
    assert get_model_config("llama-test").quantization == "none"


def test_engine_generates_with_int4_weights():
    """maybe_quantize(int4) + InferenceEngine: greedy decode runs,
    outputs are valid ids, and repeated runs are deterministic."""
    cfg = get_model_config("llama-test-int4")
    params = maybe_quantize(
        init_full_params(jax.random.PRNGKey(0), get_model_config(
            "llama-test")), cfg)
    eng = InferenceEngine(cfg, params, max_seq=32, sampling=GREEDY)
    prompt = np.asarray([[3, 1, 4, 1, 5]])
    a = eng.generate(prompt, 6).tokens
    b = eng.generate(prompt, 6).tokens
    np.testing.assert_array_equal(a, b)
    assert ((a >= 0) & (a < cfg.vocab_size)).all()


def test_layer_chunked_int4_init_matches_rewrap():
    """init_full_params(quantize=True) on an -int4 config produces the
    same tree structure (and group) as quantizing a dense init."""
    cfg = get_model_config("llama-test-int4")
    chunked = init_full_params(jax.random.PRNGKey(0), cfg, quantize=True)
    wq = chunked.layers["wq"]
    assert isinstance(wq, QuantizedArray4)
    assert wq.shape == (cfg.num_layers, cfg.hidden_size,
                        cfg.num_heads * cfg.head_dim)
    rewrap = maybe_quantize(
        init_full_params(jax.random.PRNGKey(0), get_model_config(
            "llama-test")), cfg)
    assert rewrap.layers["wq"].group == wq.group
    assert rewrap.layers["wq"].q.shape == wq.q.shape
    assert rewrap.layers["wq"].scale.shape == wq.scale.shape


def test_stage_slicing_preserves_packing():
    """Pipeline stage slicing cuts the LAYER axis; packed q and
    group scales both carry it, so a 2-stage split decodes like the
    full model."""
    cfg = get_model_config("llama-test-int4")
    params = init_full_params(jax.random.PRNGKey(0), cfg, quantize=True)
    s0 = slice_stage(params, cfg, StageSpec(0, 2, 0, 2))
    wq = s0.layers["wq"]
    assert isinstance(wq, QuantizedArray4)
    assert wq.shape[0] == 2 and wq.group == params.layers["wq"].group


def test_tp_mesh_rejected_loudly():
    from distributed_inference_demo_tpu.parallel import (MeshConfig,
                                                         make_mesh)
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)

    cfg = get_model_config("llama-test-int4")
    params = init_full_params(jax.random.PRNGKey(0), cfg, quantize=True)
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    with pytest.raises(ValueError, match="int4"):
        shard_engine_params(params, cfg, mesh)


def test_moe_int4_engine_runs():
    """int4 quantizes the expert stacks too (E axis rides the leading
    axes); the mixtral family engine decodes with packed experts."""
    cfg = get_model_config("mixtral-test-int4")
    params = init_full_params(jax.random.PRNGKey(0), cfg, quantize=True)
    assert isinstance(params.layers["w_gate"], QuantizedArray4)
    eng = InferenceEngine(cfg, params, max_seq=32, sampling=GREEDY)
    toks = eng.generate(np.asarray([[3, 1, 4, 1]]), 4).tokens
    assert ((toks >= 0) & (toks < cfg.vocab_size)).all()
