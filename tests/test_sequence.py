"""Sequence/context parallelism: ring attention + sp-sharded-cache decode.

Validates the long-context path (absent in the reference, SURVEY.md §5.7) on
the virtual 8-device CPU mesh: blockwise ring attention must match dense
causal attention exactly (same math, different schedule), and full
sequence-parallel generation must match single-device generation token for
token.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P
from distributed_inference_demo_tpu.parallel.compat import shard_map

from distributed_inference_demo_tpu.models import (
    KVCache, StageSpec, get_model_config)
from distributed_inference_demo_tpu.models.decoder import (
    init_full_params, stage_forward)
from distributed_inference_demo_tpu.ops.attention import (
    alibi_slopes, attention)
from distributed_inference_demo_tpu.ops.ring_attention import (
    ring_self_attention, sp_decode_attention)
from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
from distributed_inference_demo_tpu.parallel.sequence import (
    make_sp_generate_fn)


SP = 4


@pytest.fixture(scope="module")
def sp_mesh(devices):
    return make_mesh(MeshConfig(sp=SP), devices[:SP])


def _dense_causal(q, k, v, slopes=None):
    """Reference: ops.attention with cache == the full sequence (the cache
    layout is head-major [b, nkv, S, hd], so transpose the fresh K/V)."""
    L = q.shape[1]
    q_pos = jnp.broadcast_to(jnp.arange(L), (q.shape[0], L))
    return attention(q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
                     q_pos, jnp.asarray(L, jnp.int32), slopes)


@pytest.mark.parametrize("alibi", [
    False, pytest.param(True, marks=pytest.mark.slow)])
def test_ring_self_attention_matches_dense(sp_mesh, alibi):
    b, L, nh, nkv, hd = 2, 32, 4, 2 if not alibi else 4, 8
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, L, nh, hd), jnp.float32)
    k = jnp.asarray(rng.randn(b, L, nkv, hd), jnp.float32)
    v = jnp.asarray(rng.randn(b, L, nkv, hd), jnp.float32)
    slopes = alibi_slopes(nh) if alibi else None

    expected = _dense_causal(q, k, v, slopes)

    ring = shard_map(
        lambda q, k, v: ring_self_attention(q, k, v, "sp", slopes=slopes),
        mesh=sp_mesh, in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"), check_vma=False)
    got = ring(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def test_sp_decode_attention_matches_dense(sp_mesh):
    """Decode vs a cache whose 20 valid positions are spread over 4 shards."""
    b, nh, nkv, hd = 2, 4, 2, 8
    s_loc, valid_per_rank = 8, 5
    L = SP * valid_per_rank                      # 20 filled positions
    rng = np.random.RandomState(1)
    k_dense = jnp.asarray(rng.randn(b, L, nkv, hd), jnp.float32)
    v_dense = jnp.asarray(rng.randn(b, L, nkv, hd), jnp.float32)
    q = jnp.asarray(rng.randn(b, 1, nh, hd), jnp.float32)
    q_pos = jnp.full((b, 1), L, jnp.int32)       # new token at position L

    expected = attention(q, k_dense.transpose(0, 2, 1, 3),
                         v_dense.transpose(0, 2, 1, 3), q_pos,
                         jnp.asarray(L, jnp.int32), None)

    # scatter the dense cache into the sharded head-major layout: rank r
    # slots [0,5) hold positions [r*5, r*5+5), slots [5,8) are empty (-1).
    k_shard = np.zeros((b, nkv, SP * s_loc, hd), np.float32)
    v_shard = np.zeros_like(k_shard)
    kv_pos = np.full((SP * s_loc,), -1, np.int32)
    for r in range(SP):
        for j in range(valid_per_rank):
            slot, pos = r * s_loc + j, r * valid_per_rank + j
            k_shard[:, :, slot] = np.asarray(k_dense[:, pos])
            v_shard[:, :, slot] = np.asarray(v_dense[:, pos])
            kv_pos[slot] = pos

    dec = shard_map(
        lambda q, k, v, kp: sp_decode_attention(q, k, v, kp, q_pos, "sp"),
        mesh=sp_mesh,
        in_specs=(P(), P(None, None, "sp"), P(None, None, "sp"), P("sp")),
        out_specs=P(), check_vma=False)
    got = dec(q, jnp.asarray(k_shard), jnp.asarray(v_shard),
              jnp.asarray(kv_pos))
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


def _single_device_greedy(cfg, params, prompt, num_new, max_seq):
    """Token-for-token reference: plain cached generation, argmax."""
    b, plen = prompt.shape
    spec = StageSpec(0, 1, 0, cfg.num_layers)
    cache = KVCache.create(cfg, cfg.num_layers, b, max_seq)
    pos = jnp.broadcast_to(jnp.arange(plen), (b, plen))
    logits, cache = stage_forward(params, cfg, spec, jnp.asarray(prompt),
                                  cache, pos)
    toks = [jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)]
    for i in range(num_new - 1):
        p = jnp.full((b, 1), plen + i, jnp.int32)
        logits, cache = stage_forward(params, cfg, spec, toks[-1][:, None],
                                      cache, p)
        toks.append(jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32))
    return np.stack([np.asarray(t) for t in toks], axis=1)


# tier-1 budget: the op-level ring/decode parity tests above and the
# sp_backend [ring] e2e keep the quick-lane reps; whole-generate
# parity rides the slow lane
@pytest.mark.parametrize("model", [
    pytest.param("llama-test", marks=pytest.mark.slow),
    pytest.param("bloom-test", marks=pytest.mark.slow),
])
def test_sp_generate_matches_single_device(sp_mesh, model):
    cfg = get_model_config(model)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    b, plen, num_new, max_seq = 2, 16, 8, 32
    prompt = np.asarray(
        np.random.RandomState(2).randint(0, cfg.vocab_size, (b, plen)),
        np.int32)

    expected = _single_device_greedy(cfg, params, prompt, num_new, max_seq)

    gen = make_sp_generate_fn(cfg, sp_mesh, max_seq=max_seq,
                              num_new_tokens=num_new)
    got = gen(params, jnp.asarray(prompt), jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(got), expected)


def test_sp_generate_rejects_bad_shapes(sp_mesh):
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    gen = make_sp_generate_fn(cfg, sp_mesh, max_seq=32, num_new_tokens=4)
    with pytest.raises(ValueError, match="not divisible"):
        gen(params, jnp.zeros((1, 18), jnp.int32), jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="max_seq"):
        gen(params, jnp.zeros((1, 32), jnp.int32), jax.random.PRNGKey(0))


@pytest.mark.slow
def test_sp_generate_fp8_cache_matches_fp8_engine(sp_mesh):
    """Reduced-precision sequence-sharded cache: greedy output matches a
    single-device engine storing its cache in the same dtype (attention
    reads what the cache stores, on both sides).  Slow lane: the cross
    of two quick-covered dimensions (sp greedy parity rep + fp8 cache
    reps in test_kvcache/engine)."""
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    b, plen, num_new, max_seq = 2, 16, 8, 32
    prompt = np.asarray(
        np.random.RandomState(11).randint(0, cfg.vocab_size, (b, plen)),
        np.int32)
    want = InferenceEngine(
        cfg, params, max_seq=max_seq, sampling=SamplingParams(greedy=True),
        kv_cache_dtype="float8_e4m3fn").generate(prompt, num_new).tokens

    gen = make_sp_generate_fn(cfg, sp_mesh, max_seq=max_seq,
                              num_new_tokens=num_new,
                              kv_cache_dtype="float8_e4m3fn")
    got = gen(params, jnp.asarray(prompt), jax.random.PRNGKey(3))
    np.testing.assert_array_equal(np.asarray(got), want)
