"""Classification task path: CSV dataset → verbalizer-restricted logits →
accuracy — engine, pipeline, HTTP endpoint, and CLI.

Reference parity targets: ``Dataset.java:20-44`` (CSV loader),
``inference.cpp:220-270`` (classification inference variant),
``BackgroundService.java:233-245`` (accuracy loop).  Two rounds of
VERDICT.md flagged ``task_type="classification"`` as accepted-but-
unimplemented; these tests pin the implementation.
"""

import io
import json
import http.client
import threading
from contextlib import redirect_stdout

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_inference_demo_tpu import cli
from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport)
from distributed_inference_demo_tpu.models import (
    KVCache, StageSpec, get_model_config)
from distributed_inference_demo_tpu.models.base import (
    slice_stage, split_layer_ranges)
from distributed_inference_demo_tpu.models.decoder import (
    init_full_params, stage_forward)
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.distributed import (
    PipelineHeader, PipelineWorker, StageRuntime)
from distributed_inference_demo_tpu.tasks import (
    evaluate_classifier, load_csv_dataset)

MODEL = "llama-test"
GREEDY = SamplingParams(greedy=True)
LABELS = [7, 42, 99]   # verbalizer token ids, one per class


@pytest.fixture(scope="module")
def setup():
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64, sampling=GREEDY)
    return cfg, params, engine


def test_csv_loader(tmp_path):
    p = tmp_path / "ds.csv"
    p.write_text('hello world,pos\n"with, comma",neg\nanother,pos\n')
    ds = load_csv_dataset(str(p))
    assert ds.texts == ["hello world", "with, comma", "another"]
    assert ds.labels == [0, 1, 0]              # first-seen order
    assert ds.label_names == ["pos", "neg"]


def test_engine_classify_is_restricted_argmax(setup):
    cfg, params, engine = setup
    prompts = np.array([[5, 17, 42, 7], [9, 1, 3, 2]], np.int32)
    pred = engine.classify(prompts, LABELS)

    # manual reference: full prefill logits, slice label ids, argmax
    spec = StageSpec(0, 1, 0, cfg.num_layers)
    pos = jnp.broadcast_to(jnp.arange(4), (2, 4))
    logits, _ = stage_forward(params, cfg, spec, jnp.asarray(prompts),
                              KVCache.create(cfg, cfg.num_layers, 2, 64),
                              pos)
    want = np.argmax(np.asarray(logits[:, -1])[:, LABELS], axis=-1)
    np.testing.assert_array_equal(pred, want)
    with pytest.raises(ValueError, match="label_token_ids"):
        engine.classify(prompts, [5])


def test_pipeline_classify_matches_engine_and_accuracy(setup):
    """The e2e the VERDICT asked for: accuracy over a live 2-stage
    pipeline, predictions identical to the single-chip engine."""
    cfg, params, engine = setup
    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    t0, t1 = LoopbackTransport("s0", net), LoopbackTransport("s1", net)
    header = PipelineHeader(
        StageRuntime(cfg, specs[0], slice_stage(params, cfg, specs[0]), 64,
                     GREEDY),
        t0, next_id="s1", step_timeout=60)
    worker = PipelineWorker(
        StageRuntime(cfg, specs[1], slice_stage(params, cfg, specs[1]), 64,
                     GREEDY),
        t1, next_id=None, header_id="s0", step_timeout=60)
    th = threading.Thread(target=worker.serve_forever, daemon=True)
    th.start()

    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, (1, 6)).astype(np.int32)
               for _ in range(5)]
    try:
        preds = header.classify_many(prompts, LABELS, pool_size=2)
        want = [engine.classify(p, LABELS) for p in prompts]
        for got, exp in zip(preds, want):
            np.testing.assert_array_equal(got, exp)

        # accuracy loop over the pipeline, self-consistent labels = 1.0;
        # flipped labels measure the complement
        labels = [int(w[0]) for w in want]
        result = evaluate_classifier(
            lambda b: np.concatenate(
                header.classify_many([b], LABELS)),
            prompts, labels, batch_size=2)
        assert result["accuracy"] == 1.0 and result["total"] == 5
        flipped = [(l + 1) % len(LABELS) for l in labels]
        result2 = evaluate_classifier(
            lambda b: np.concatenate(header.classify_many([b], LABELS)),
            prompts, flipped, batch_size=2)
        assert result2["accuracy"] == 0.0
        assert not header.rt.caches          # freed synchronously
        deadline = __import__("time").monotonic() + 10
        while worker.rt.caches and __import__("time").monotonic() < deadline:
            __import__("time").sleep(0.05)   # end:{rid} is async
        assert not worker.rt.caches
    finally:
        header.shutdown_pipeline()
        th.join(timeout=30)


def test_evaluate_classifier_ragged_lengths(setup):
    _, _, engine = setup
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 250, (1, n)).astype(np.int32)
               for n in (4, 6, 4, 6, 6)]
    want = [int(engine.classify(p, LABELS)[0]) for p in prompts]
    res = evaluate_classifier(lambda b: engine.classify(b, LABELS),
                              prompts, want, batch_size=2)
    assert res["accuracy"] == 1.0
    assert res["predictions"] == want


def test_http_classify_endpoint(setup):
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)
    _, _, engine = setup
    server = InferenceHTTPServer(engine, port=0, model_name=MODEL)
    server.start()
    try:
        conn = http.client.HTTPConnection(server.host, server.port,
                                          timeout=60)
        body = {"prompt_ids": [[5, 17, 42, 7]], "label_token_ids": LABELS}
        conn.request("POST", "/classify", body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        data = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        want = engine.classify(np.asarray([[5, 17, 42, 7]]), LABELS)
        assert data["labels"] == want.tolist()
    finally:
        server.shutdown()


def test_cli_classify_accuracy(tmp_path, setup):
    """CLI dataset run: pre-tokenized text column, accuracy JSON out."""
    _, _, engine = setup
    rng = np.random.RandomState(1)
    rows, names = [], ["a", "b", "c"]
    for _ in range(4):
        ids = rng.randint(0, 250, 5)
        pred = int(engine.classify(ids[None, :], LABELS)[0])
        rows.append((" ".join(map(str, ids)), names[pred]))
    csv_path = tmp_path / "ds.csv"
    csv_path.write_text("".join(f'"{t}",{l}\n' for t, l in rows))
    ds = load_csv_dataset(str(csv_path))
    label_ids = ",".join(str(LABELS[names.index(n)])
                         for n in ds.label_names)

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = cli.main(["classify", "--model", MODEL, "--dataset",
                       str(csv_path), "--label-token-ids", label_ids,
                       "--max-seq", "64", "--attn-backend", "jnp",
                       "--greedy"])
    assert rc == 0
    out = json.loads(buf.getvalue())
    assert out["total"] == 4 and out["accuracy"] == 1.0
