"""Block-table paged attention (ops/paged_attention.py) vs the dense
reference: the property the whole paged layout stands on is that
attending through a block table is bit-for-bit the same computation as
attending a linear cache holding the same K/V.

The sweep covers the shapes that break naive implementations: ragged
per-row lengths, lengths exactly on block boundaries, single-token tail
blocks, sentinel (unallocated) table entries, GQA group sizes from MHA
to 8x, and ALiBi.  The Pallas kernel runs in interpret mode on CPU
against the same oracle the XLA fallback uses.
"""

import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from distributed_inference_demo_tpu.ops.attention import attention
from distributed_inference_demo_tpu.ops.paged_attention import (
    make_paged_attn_impl, paged_flash_attention, paged_gather_attention,
    paged_prefill_attention, write_paged_kv)


def _random_paged(rng, b, nkv, hd, bt, W, lens, extra_pages=3,
                  append_room=0):
    """Pages + tables realizing per-row lengths ``lens``; unallocated
    tail entries get the sentinel (>= num_pages).  ``append_room``
    allocates pages for that many tokens past each length (the engine
    preallocates a request's whole prompt+max_new table)."""
    needed = sum(-(-(int(l) + append_room) // bt) for l in lens)
    N = needed + extra_pages
    pk = jnp.asarray(rng.standard_normal((N, nkv, bt, hd)), jnp.float32)
    pv = jnp.asarray(rng.standard_normal((N, nkv, bt, hd)), jnp.float32)
    tables = np.full((b, W), N + 7, np.int32)
    nxt = 0
    for i, l in enumerate(lens):
        for j in range(-(-(int(l) + append_room) // bt)):
            tables[i, j] = nxt
            nxt += 1
    return pk, pv, jnp.asarray(tables), N


def _linearize(pk, pv, tables, N, bt, W):
    """The dense cache a row's table describes (zeros where sentinel)."""
    b = tables.shape[0]
    nkv, hd = pk.shape[1], pk.shape[3]
    k_lin = np.zeros((b, nkv, W * bt, hd), np.float32)
    v_lin = np.zeros_like(k_lin)
    tt = np.asarray(tables)
    for i in range(b):
        for j in range(W):
            if tt[i, j] < N:
                k_lin[i, :, j * bt:(j + 1) * bt] = np.asarray(pk)[tt[i, j]]
                v_lin[i, :, j * bt:(j + 1) * bt] = np.asarray(pv)[tt[i, j]]
    return jnp.asarray(k_lin), jnp.asarray(v_lin)


# lengths chosen to hit: mid-block, exact block boundary, single-token
# tail block, single-token sequence, full table
SWEEP = [
    dict(nh=4, nkv=2, hd=16, bt=8, W=4, lens=[5, 8, 17]),
    dict(nh=8, nkv=1, hd=8, bt=16, W=3, lens=[1, 33, 48]),
    dict(nh=2, nkv=2, hd=32, bt=8, W=2, lens=[16, 9]),
    dict(nh=8, nkv=4, hd=8, bt=24, W=5, lens=[25, 120, 24, 1]),
]


@pytest.mark.parametrize("case", SWEEP)
@pytest.mark.parametrize("alibi", [False, True])
def test_gather_matches_dense_reference(case, alibi):
    rng = np.random.default_rng(hash(str(case)) % 2**32)
    lens = case["lens"]
    b, bt, W = len(lens), case["bt"], case["W"]
    pk, pv, tables, N = _random_paged(rng, b, case["nkv"], case["hd"],
                                      bt, W, lens)
    q = jnp.asarray(rng.standard_normal((b, 1, case["nh"], case["hd"])),
                    jnp.float32)
    qpos = jnp.asarray([l - 1 for l in lens], jnp.int32)[:, None]
    slopes = None
    if alibi:
        from distributed_inference_demo_tpu.ops.attention import (
            alibi_slopes)
        slopes = alibi_slopes(case["nh"])

    k_lin, v_lin = _linearize(pk, pv, tables, N, bt, W)
    ref = attention(q, k_lin, v_lin, qpos, jnp.int32(W * bt), slopes)
    got = paged_gather_attention(q, pk, pv, tables, qpos, slopes)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("case", SWEEP)
def test_pallas_interpret_matches_gather(case):
    """The TPU kernel (interpret mode) against the XLA fallback — same
    pages, same tables, f32 tolerance (online softmax vs one-shot)."""
    if case["bt"] % 8:
        pytest.skip("pallas path needs 8-aligned pages")
    rng = np.random.default_rng(hash(str(case)) % 2**32)
    lens = case["lens"]
    b, bt, W = len(lens), case["bt"], case["W"]
    pk, pv, tables, N = _random_paged(rng, b, case["nkv"], case["hd"],
                                      bt, W, lens)
    q = jnp.asarray(rng.standard_normal((b, 1, case["nh"], case["hd"])),
                    jnp.float32)
    qpos = jnp.asarray([l - 1 for l in lens], jnp.int32)[:, None]
    ref = paged_gather_attention(q, pk, pv, tables, qpos, None)
    got = paged_flash_attention(q, pk, pv, tables,
                                jnp.asarray(lens, jnp.int32), None,
                                interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# per-row starts hit: chunk from zero, chunk mid-page, chunk crossing a
# page boundary, deep prior context; chunk lengths hit sub-page, exact
# page, and multi-page spans (rows = chunk x group padded to 8)
PREFILL_SWEEP = [
    dict(nh=4, nkv=2, hd=16, bt=8, W=6, chunk=8, starts=[0, 8, 19]),
    dict(nh=8, nkv=2, hd=8, bt=8, W=8, chunk=5, starts=[3, 0, 40]),
    dict(nh=2, nkv=2, hd=32, bt=16, W=3, chunk=16, starts=[0, 13]),
    dict(nh=4, nkv=4, hd=8, bt=8, W=5, chunk=17, starts=[1, 20]),
]


@pytest.mark.parametrize("case", PREFILL_SWEEP)
@pytest.mark.parametrize("mode", ["f32", "alibi", "int8"])
def test_pallas_prefill_interpret_matches_gather(case, mode):
    """The ISSUE-15 prefill kernel (interpret mode) against the XLA
    gather fallback: a chunk's queries attend causally over prior pages
    plus in-chunk keys already written to the pool (write-before-attend
    contract), per-row ragged starts, GQA row packing, ALiBi, and int8
    sidecar dequant.  f32 tolerance — the online softmax reduces in a
    different order than the one-shot gather."""
    rng = np.random.default_rng(hash(str(case) + mode) % 2**32)
    starts, chunk = case["starts"], case["chunk"]
    b, bt, W = len(starts), case["bt"], case["W"]
    lens = [s + chunk for s in starts]     # in-chunk keys already paged
    pk, pv, tables, N = _random_paged(rng, b, case["nkv"], case["hd"],
                                      bt, W, lens)
    if mode == "int8":
        from distributed_inference_demo_tpu.ops.quant import (
            quantize_kv_pages)
        pk, pv = quantize_kv_pages(pk, 8), quantize_kv_pages(pv, 8)
    q = jnp.asarray(
        rng.standard_normal((b, chunk, case["nh"], case["hd"])),
        jnp.float32)
    qpos = (jnp.asarray(starts, jnp.int32)[:, None]
            + jnp.arange(chunk, dtype=jnp.int32)[None, :])
    slopes = None
    if mode == "alibi":
        from distributed_inference_demo_tpu.ops.attention import (
            alibi_slopes)
        slopes = alibi_slopes(case["nh"])
    ref = paged_gather_attention(q, pk, pv, tables, qpos, slopes)
    got = paged_prefill_attention(q, pk, pv, tables, qpos, slopes,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_prefill_kernel_rejects_int4_and_unaligned_pages():
    """int4 packed pages and non-8-aligned page sizes stay on the
    gather fallback — the kernel refuses them loudly instead of
    decoding garbage nibbles."""
    from distributed_inference_demo_tpu.ops.quant import (
        quantize_kv_pages)
    rng = np.random.default_rng(7)
    pk, pv, tables, N = _random_paged(rng, 1, 2, 8, 8, 4, [8])
    q = jnp.asarray(rng.standard_normal((1, 8, 4, 8)), jnp.float32)
    qpos = jnp.arange(8, dtype=jnp.int32)[None, :]
    with pytest.raises(ValueError, match="gather"):
        paged_prefill_attention(q, quantize_kv_pages(pk, 4),
                                quantize_kv_pages(pv, 4), tables, qpos,
                                interpret=True)
    pk3, pv3, tables3, _ = _random_paged(rng, 1, 2, 8, 12, 4, [12])
    q3 = jnp.asarray(rng.standard_normal((1, 12, 4, 8)), jnp.float32)
    qpos3 = jnp.arange(12, dtype=jnp.int32)[None, :]
    with pytest.raises(ValueError, match="block_tokens"):
        paged_prefill_attention(q3, pk3, pv3, tables3, qpos3,
                                interpret=True)


def test_write_lands_in_right_page_and_offset():
    rng = np.random.default_rng(0)
    b, nkv, hd, bt, W = 3, 2, 8, 8, 4
    lens = [5, 8, 17]
    pk, pv, tables, N = _random_paged(rng, b, nkv, hd, bt, W, lens,
                                      append_room=1)
    k_new = jnp.asarray(rng.standard_normal((b, 1, nkv, hd)), jnp.float32)
    v_new = k_new * 2
    pos = jnp.asarray(lens, jnp.int32)[:, None]   # append position
    pk2, pv2 = write_paged_kv(pk, pv, k_new, v_new, tables, pos)
    tt = np.asarray(tables)
    for i, l in enumerate(lens):
        page, off = tt[i, l // bt], l % bt
        assert page < N, "append position must have an allocated page"
        np.testing.assert_array_equal(np.asarray(pk2)[page, :, off],
                                      np.asarray(k_new)[i, 0])
        np.testing.assert_array_equal(np.asarray(pv2)[page, :, off],
                                      np.asarray(v_new)[i, 0])


def test_write_through_sentinel_drops():
    """A freed slot's writes route through sentinel entries and vanish —
    no pool page may change (the paged stale-slot guarantee)."""
    rng = np.random.default_rng(1)
    pk, pv, tables, N = _random_paged(rng, 2, 2, 8, 8, 3, [8, 16])
    all_sentinel = jnp.full_like(tables, N + 7)
    k_new = jnp.ones((2, 1, 2, 8), jnp.float32)
    pk2, pv2 = write_paged_kv(pk, pv, k_new, k_new, all_sentinel,
                              jnp.asarray([[3], [9]], jnp.int32))
    np.testing.assert_array_equal(np.asarray(pk2), np.asarray(pk))
    np.testing.assert_array_equal(np.asarray(pv2), np.asarray(pv))


def test_impl_binds_tables_and_matches_manual_sequence():
    """The attn_impl seam: bind + impl inside a jit reproduces
    write-then-attend done by hand."""
    rng = np.random.default_rng(2)
    b, nkv, nh, hd, bt, W = 2, 2, 4, 8, 8, 3
    lens = [7, 12]
    pk, pv, tables, N = _random_paged(rng, b, nkv, hd, bt, W, lens)
    impl, bind = make_paged_attn_impl(bt, backend="xla")
    q = jnp.asarray(rng.standard_normal((b, 1, nh, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, 1, nkv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, 1, nkv, hd)), jnp.float32)
    pos = jnp.asarray(lens, jnp.int32)[:, None]

    @jax.jit
    def step(q, k, v, pk, pv, tables, pos):
        bind(tables)
        return impl(q, k, v, pk, pv, pos, jnp.int32(0), None)

    out, pk2, pv2 = step(q, k, v, pk, pv, tables, pos)
    epk, epv = write_paged_kv(pk, pv, k, v, tables, pos)
    eout = paged_gather_attention(q, epk, epv, tables, pos, None)
    np.testing.assert_array_equal(np.asarray(pk2), np.asarray(epk))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(eout))
