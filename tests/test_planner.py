"""Planner tests: cost model sanity, optimizer behavior vs round-robin,
memory constraints, plan caching."""

import pytest

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.planner import (
    DeviceProfile, PlanError, load_cached_plan, model_cost_profile,
    plan_partition, round_robin_plan, save_plan_cache)


def dev(i, flops=1e12, mem=16 << 30, platform="cpu", chips=1):
    return DeviceProfile(device_id=f"d{i}", address=f"10.0.0.{i}:9000",
                         flops_per_sec=flops, memory_bytes=mem,
                         platform=platform, chips=chips,
                         egress_bandwidth=1e9, egress_latency=1e-3)


# ---------------------------------------------------------------- cost model

def test_cost_profile_scales_with_architecture():
    small = model_cost_profile(get_model_config("llama-test"))
    big = model_cost_profile(get_model_config("llama-3-8b"))
    assert big.layers[0].flops > small.layers[0].flops * 100
    assert big.total_param_bytes > small.total_param_bytes * 100
    # 8B params at bf16 ≈ 16 GB within 25%
    assert 12e9 < big.total_param_bytes < 20e9


def test_cost_profile_int8_halves_weight_bytes():
    cfg = get_model_config("llama-3-8b")
    bf16 = model_cost_profile(cfg)
    int8 = model_cost_profile(cfg.replace(quantization="int8"))
    ratio = int8.total_param_bytes / bf16.total_param_bytes
    assert 0.45 < ratio < 0.55


def test_cost_profile_moe_flops_sparse():
    """Mixtral: params count all experts, flops only experts_per_token."""
    cfg = get_model_config("mixtral-test")
    prof = model_cost_profile(cfg)
    dense_equiv = model_cost_profile(cfg.replace(num_experts=0))
    # 4 experts' params well above dense (layer cost includes attention);
    # flops ~2x dense mlp (2 of 4 experts routed)
    assert prof.layers[0].param_bytes > 2.5 * dense_equiv.layers[0].param_bytes
    assert prof.layers[0].flops < 3 * dense_equiv.layers[0].flops


# ------------------------------------------------------------------ planner

def test_round_robin_even_split():
    cfg = get_model_config("tinyllama-1.1b")  # 22 layers
    plan = round_robin_plan(cfg, "tinyllama-1.1b", [dev(0), dev(1)])
    assert plan.stage_ranges == {"d0": [0, 11], "d1": [11, 22]}
    assert plan.device_graph == ["10.0.0.0:9000", "10.0.0.1:9000"]


def test_plan_homogeneous_nearly_even():
    cfg = get_model_config("tinyllama-1.1b")
    plan = plan_partition(cfg, "tinyllama-1.1b", [dev(0), dev(1)])
    sizes = [s.layer_end - s.layer_start for s in plan.stages]
    assert sum(sizes) == cfg.num_layers
    # head stage carries the LM-head flops, so it may get fewer layers,
    # but the split must not be degenerate
    assert min(sizes) >= cfg.num_layers // 4


def test_plan_fast_device_gets_more_layers():
    cfg = get_model_config("tinyllama-1.1b")
    slow, fast = dev(0, flops=1e11), dev(1, flops=1e12)
    plan = plan_partition(cfg, "tinyllama-1.1b", [slow, fast])
    n_slow = plan.stages[0].layer_end - plan.stages[0].layer_start
    n_fast = plan.stages[1].layer_end - plan.stages[1].layer_start
    assert n_fast > n_slow * 2
    # the bottleneck equals the slowest stage's step time
    assert plan.est_bottleneck_sec == pytest.approx(
        max(s.est_step_sec for s in plan.stages))


def test_plan_memory_constraint_shifts_layers():
    cfg = get_model_config("tinyllama-1.1b")
    # d0 fast but tiny memory: only a few layers fit under 0.7 headroom
    prof = model_cost_profile(cfg, ctx=128)
    per_layer = prof.layers[0].param_bytes
    tiny = dev(0, flops=1e13, mem=int(5 * per_layer / 0.7))
    big = dev(1, flops=1e11, mem=64 << 30)
    plan = plan_partition(cfg, "tinyllama-1.1b", [tiny, big], ctx=128)
    n0 = plan.stages[0].layer_end - plan.stages[0].layer_start
    assert n0 <= 5


def test_plan_infeasible_raises():
    cfg = get_model_config("tinyllama-1.1b")
    with pytest.raises(PlanError, match="no feasible"):
        plan_partition(cfg, "tinyllama-1.1b",
                       [dev(0, mem=1 << 20), dev(1, mem=1 << 20)])
    with pytest.raises(PlanError):
        plan_partition(get_model_config("llama-test"), "llama-test",
                       [dev(i) for i in range(10)])  # 10 devices, 4 layers


def test_plan_tpu_mesh_axes():
    cfg = get_model_config("llama-3-8b")
    cpu = dev(0, flops=5e11, mem=64 << 30)
    tpu = dev(1, flops=2.75e14, mem=32 << 30, platform="tpu", chips=4)
    plan = plan_partition(cfg, "llama-3-8b", [cpu, tpu])
    assert plan.stages[1].mesh_axes["tp"] == 4
    assert plan.stages[0].mesh_axes["tp"] == 1
    # TPU vastly faster -> takes the overwhelming majority of layers
    n_tpu = plan.stages[1].layer_end - plan.stages[1].layer_start
    assert n_tpu >= cfg.num_layers - 4


def test_plan_single_device_no_comm():
    cfg = get_model_config("llama-test")
    plan = plan_partition(cfg, "llama-test", [dev(0)])
    assert plan.stage_ranges == {"d0": [0, 4]}
    assert plan.stages[0].est_comm_sec == 0.0


def test_plan_cache_roundtrip(tmp_path):
    cfg = get_model_config("tinyllama-1.1b")
    plan = plan_partition(cfg, "tinyllama-1.1b", [dev(0), dev(1)])
    path = str(tmp_path / "plan.json")
    save_plan_cache(path, plan)
    # matching model + device set -> reload (server.py:805-820)
    got = load_cached_plan(path, "tinyllama-1.1b", ["d0", "d1"])
    assert got is not None
    assert got.stage_ranges == plan.stage_ranges
    assert got.est_bottleneck_sec == pytest.approx(plan.est_bottleneck_sec)
    # fleet changed -> no reload, forces replan (improves on reference)
    assert load_cached_plan(path, "tinyllama-1.1b", ["d0", "d2"]) is None
    assert load_cached_plan(path, "llama-3-8b", ["d0", "d1"]) is None
    assert load_cached_plan(str(tmp_path / "absent.json"),
                            "tinyllama-1.1b", ["d0", "d1"]) is None


def test_stage_specs_cover_model():
    cfg = get_model_config("tinyllama-1.1b")
    plan = plan_partition(cfg, "tinyllama-1.1b", [dev(0), dev(1), dev(2)])
    specs = plan.stage_specs()
    assert specs[0].is_first and specs[-1].is_last
    assert specs[0].layer_start == 0
    assert specs[-1].layer_end == cfg.num_layers
    for a, b in zip(specs, specs[1:]):
        assert a.layer_end == b.layer_start
