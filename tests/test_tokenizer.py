"""Tokenizer tests: native C++ vs pure-Python twin vs HuggingFace.

The HF ``tokenizers`` library (in the image) is used as ground truth: a
ByteLevel-BPE tokenizer trained in-test plus a hand-built metaspace
(llama-style) tokenizer.json.  The reference shipped its tokenizer stack
(Rust + sentencepiece) with zero project-owned tests (SURVEY.md §4).
"""

import json

import pytest

from distributed_inference_demo_tpu.tokenizer import (
    PyBPETokenizer, Tokenizer, TokenizerSpec)

TEXTS = [
    "Hello world! This is a test.",
    "The year 2024's results weren't great...",
    "  leading spaces and\nnewlines\t tabs  ",
    "héllo wörld ünïcode ¡Ω≈ç√",
    "I'll we've don't it's 'quoted'",
    "x",
    "",
    "   ",
    "a  b   c",
]


@pytest.fixture(scope="module")
def bytelevel_json(tmp_path_factory):
    """Train a small ByteLevel BPE with the real HF tokenizers library."""
    from tokenizers import Tokenizer as HFTok
    from tokenizers.models import BPE
    from tokenizers.trainers import BpeTrainer
    from tokenizers import pre_tokenizers, decoders

    tok = HFTok(BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False,
                                                 use_regex=True)
    tok.decoder = decoders.ByteLevel()
    corpus = [t for t in TEXTS if t.strip()] * 50 + [
        "the quick brown fox jumps over the lazy dog",
        "pipeline parallel inference on tpu meshes",
    ] * 50
    trainer = BpeTrainer(vocab_size=400, special_tokens=["<s>", "</s>"],
                         show_progress=False)
    tok.train_from_iterator(corpus, trainer)
    return tok.to_str()


@pytest.fixture(scope="module")
def metaspace_json():
    """Hand-built llama-style metaspace BPE with byte fallback."""
    pieces = ["<unk>", "<s>", "</s>"]
    pieces += [f"<0x{b:02X}>" for b in range(256)]
    base = list("▁abcdefghijklmnopqrstuvwxyz.!?'")
    words = ["▁hello", "▁world", "▁the", "▁test", "hel", "llo", "wor",
             "ld", "th", "he", "st", "▁t", "▁w", "▁h", "es", "te"]
    vocab = {}
    for p in pieces + base + words:
        if p not in vocab:
            vocab[p] = len(vocab)
    merges = [["th", "e"], ["h", "e"], ["e", "s"], ["t", "e"],
              ["▁", "t"], ["▁", "w"], ["▁", "h"],
              ["he", "l"], ["l", "lo"], ["l", "o"], ["l", "l"],
              ["hel", "lo"], ["▁h", "hello"],
              ["wor", "ld"], ["w", "or"], ["o", "r"], ["w", "o"]]
    merges = [m for m in merges
              if m[0] in vocab and m[1] in vocab and (m[0] + m[1]) in vocab]
    return json.dumps({
        "model": {"type": "BPE", "vocab": vocab,
                  "merges": [f"{a} {b}" for a, b in merges],
                  "unk_token": "<unk>", "byte_fallback": True},
        "pre_tokenizer": {"type": "Metaspace", "replacement": "▁",
                          "prepend_scheme": "always", "split": True},
        "decoder": {"type": "Sequence", "decoders": [
            {"type": "Replace", "pattern": {"String": "▁"}, "content": " "},
            {"type": "ByteFallback"},
            {"type": "Fuse"},
            {"type": "Strip", "content": " ", "start": 1, "stop": 0},
        ]},
        "added_tokens": [
            {"id": i, "content": c, "special": True, "single_word": False,
             "lstrip": False, "rstrip": False, "normalized": False}
            for i, c in ((0, "<unk>"), (1, "<s>"), (2, "</s>"))
        ],
    })


@pytest.mark.parametrize("text", TEXTS, ids=range(len(TEXTS)))
def test_bytelevel_matches_hf(bytelevel_json, text):
    ours_native = Tokenizer.from_json(bytelevel_json, backend="native")
    ours_py = Tokenizer.from_json(bytelevel_json, backend="python")
    hf = Tokenizer.from_json(bytelevel_json, backend="hf")
    assert ours_native.backend == "native"
    ref = hf.encode(text)
    assert ours_py.encode(text) == ref
    assert ours_native.encode(text) == ref
    # decode round-trips the original text exactly (byte-level is lossless)
    assert ours_native.decode(ref) == text
    assert ours_py.decode(ref) == text


@pytest.mark.parametrize("text", [
    "hello world", "the test.", "hello", " hello  world ",
    "unknown UPPER chars 123", "héllo"])
def test_metaspace_matches_hf(metaspace_json, text):
    ours_native = Tokenizer.from_json(metaspace_json, backend="native")
    ours_py = Tokenizer.from_json(metaspace_json, backend="python")
    hf = Tokenizer.from_json(metaspace_json, backend="hf")
    ref = hf.encode(text)
    assert ours_py.encode(text) == ref, (text, ours_py.encode(text), ref)
    assert ours_native.encode(text) == ref
    assert ours_py.decode(ref) == ours_native.decode(ref) == hf.decode(ref)


@pytest.mark.quick
def test_special_token_split(metaspace_json):
    tok = Tokenizer.from_json(metaspace_json, backend="python")
    ids = tok.encode("<s>hello</s>")
    assert ids[0] == tok.bos_id == 1
    assert ids[-1] == tok.eos_id == 2
    assert tok.is_eos(ids[-1])
    nat = Tokenizer.from_json(metaspace_json, backend="native")
    assert nat.encode("<s>hello</s>") == ids
    # skip_special drops them on decode
    assert "<s>" not in tok.decode(ids)
    assert "<s>" in tok.decode(ids, skip_special=False)


def test_surface_parity(metaspace_json):
    """tokenizers_cpp.h:25-48 surface on both backends."""
    for backend in ("python", "native"):
        tok = Tokenizer.from_json(metaspace_json, backend=backend)
        i = tok.token_to_id("▁hello")
        assert i >= 0
        assert tok.id_to_token(i) == "▁hello"
        assert tok.token_to_id("definitely-not-a-token") == -1
        assert tok.id_to_token(10 ** 6) is None
        assert tok.vocab_size() > 256


def test_bos_eos_helpers(metaspace_json):
    tok = Tokenizer.from_json(metaspace_json, backend="python")
    plain = tok.encode("hello")
    wrapped = tok.encode("hello", add_bos=True, add_eos=True)
    assert wrapped == [tok.bos_id] + plain + [tok.eos_id]


def test_byte_fallback(metaspace_json):
    tok = Tokenizer.from_json(metaspace_json, backend="python")
    nat = Tokenizer.from_json(metaspace_json, backend="native")
    ids = tok.encode("Z")  # uppercase: not in vocab -> byte fallback
    assert ids == nat.encode("Z")
    assert tok.decode(ids) == "Z"
    assert nat.decode(ids) == "Z"
