"""Engine tests: fused-scan vs streamed decode parity, capacity guard."""

import jax
import numpy as np
import pytest

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine


@pytest.fixture(scope="module")
def engine():
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    return InferenceEngine(cfg, params, max_seq=64,
                           sampling=SamplingParams(greedy=True))


def test_generate_shapes_and_throughput(engine):
    prompt = np.arange(8).reshape(2, 4)
    res = engine.generate(prompt, max_new_tokens=10)
    assert res.tokens.shape == (2, 10)
    assert res.tokens.dtype == np.int32
    assert np.isfinite(res.tokens_per_second)


@pytest.mark.quick
def test_stream_matches_fused_scan(engine):
    """The streaming path must produce the same tokens as the fused scan
    (both greedy, same seed)."""
    prompt = np.asarray([[3, 14, 15, 92, 65]])
    fused = engine.generate(prompt, max_new_tokens=8, seed=7).tokens
    streamed = np.stack(list(engine.generate_stream(prompt, 8, seed=7)), 1)
    np.testing.assert_array_equal(fused, streamed)


@pytest.mark.parametrize("plen", [
    pytest.param(7, marks=pytest.mark.slow), 8,
    pytest.param(9, marks=pytest.mark.slow), 17])
def test_chunked_prefill_matches_whole(engine, plen):
    """Chunked prefill (C=8) must produce the same greedy tokens as
    whole-prompt prefill for every remainder shape: plen < C, == C,
    == C+1, and spanning 3 chunks."""
    cfg = engine.cfg
    chunked = InferenceEngine(cfg, engine.params, max_seq=64,
                              sampling=SamplingParams(greedy=True),
                              prefill_chunk=8)
    prompt = (np.arange(2 * plen).reshape(2, plen) % 199).astype(np.int32)
    want = engine.generate(prompt, 10).tokens
    got = chunked.generate(prompt, 10).tokens
    np.testing.assert_array_equal(want, got)


def test_chunked_prefill_stream_and_classify(engine):
    cfg = engine.cfg
    chunked = InferenceEngine(cfg, engine.params, max_seq=64,
                              sampling=SamplingParams(greedy=True),
                              prefill_chunk=4)
    prompt = np.asarray([[3, 14, 15, 92, 65, 35, 89, 79, 3]])
    fused = chunked.generate(prompt, 6).tokens
    streamed = np.stack(list(chunked.generate_stream(prompt, 6)), 1)
    np.testing.assert_array_equal(fused, streamed)
    labels = engine.classify(prompt, [5, 9])
    labels_chunked = chunked.classify(prompt, [5, 9])
    np.testing.assert_array_equal(labels, labels_chunked)


def test_prefill_chunk_validation(engine):
    with pytest.raises(ValueError, match="prefill_chunk"):
        InferenceEngine(engine.cfg, engine.params, max_seq=64,
                        prefill_chunk=0)
    with pytest.raises(ValueError, match="prefill_chunk"):
        InferenceEngine(engine.cfg, engine.params, max_seq=64,
                        prefill_chunk=65)


def test_chunked_prefill_padded_past_capacity(engine):
    """Regression: prompt whose chunk-padded length exceeds max_seq.
    The final chunk must left-shift (aligned last window) instead of
    letting dynamic_update_slice clamp into — and corrupt — valid KV.
    max_seq=30, C=8, plen=26: padding would want slot 31."""
    cfg = engine.cfg
    whole = InferenceEngine(cfg, engine.params, max_seq=30,
                            sampling=SamplingParams(greedy=True))
    chunked = InferenceEngine(cfg, engine.params, max_seq=30,
                              sampling=SamplingParams(greedy=True),
                              prefill_chunk=8)
    prompt = (np.arange(2 * 26).reshape(2, 26) % 199).astype(np.int32)
    want = whole.generate(prompt, 4).tokens
    got = chunked.generate(prompt, 4).tokens
    np.testing.assert_array_equal(want, got)


def test_tp_mesh_engine_matches_single(engine):
    """InferenceEngine(mesh=tp2) greedy output must equal the single-chip
    engine's — BASELINE config #3 (TP serving) as an engine surface."""
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)

    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    params = shard_engine_params(engine.params, engine.cfg, mesh)
    tp_engine = InferenceEngine(engine.cfg, params, max_seq=64,
                                sampling=SamplingParams(greedy=True),
                                mesh=mesh)
    prompt = np.asarray([[3, 14, 15, 92], [7, 6, 5, 4]])
    want = engine.generate(prompt, 10).tokens
    got = tp_engine.generate(prompt, 10).tokens
    np.testing.assert_array_equal(want, got)
    # streaming and logprobs ride the same fwd seam
    streamed = np.stack(list(tp_engine.generate_stream(prompt, 6)), 1)
    np.testing.assert_array_equal(want[:, :6], streamed)
    lp = tp_engine.generate(prompt, 4, logprobs=True)
    assert lp.logprobs.shape == (2, 4) and (lp.logprobs <= 0).all()


def test_tp_mesh_validation(engine):
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh

    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    with pytest.raises(ValueError, match="incompatible"):
        InferenceEngine(engine.cfg, engine.params, max_seq=64, mesh=mesh,
                        attn_backend="flash")


def test_fp8_kv_cache_under_tp_mesh(engine):
    """kv_cache_dtype composes with a tp mesh: the insert cast and read
    upcast run inside the shard, so tp-sharded fp8 decode must equal
    single-device fp8 decode bit-exactly."""
    import jax.numpy as jnp
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)

    single = InferenceEngine(engine.cfg, engine.params, max_seq=64,
                             sampling=SamplingParams(greedy=True),
                             kv_cache_dtype="float8_e4m3fn")
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    params = shard_engine_params(engine.params, engine.cfg, mesh)
    tp_fp8 = InferenceEngine(engine.cfg, params, max_seq=64,
                             sampling=SamplingParams(greedy=True),
                             kv_cache_dtype="float8_e4m3fn", mesh=mesh)
    assert tp_fp8.new_cache(2).keys.dtype == jnp.float8_e4m3fn
    prompt = np.asarray([[3, 14, 15, 92], [7, 6, 5, 4]])
    np.testing.assert_array_equal(single.generate(prompt, 10).tokens,
                                  tp_fp8.generate(prompt, 10).tokens)


def test_logprobs(engine):
    """logprobs=True returns the raw log-softmax of each emitted token:
    negative, and for greedy decoding equal to the max log-softmax (which
    we cross-check by re-scoring the sequence)."""
    import jax.numpy as jnp
    from distributed_inference_demo_tpu.models.base import KVCache, StageSpec
    from distributed_inference_demo_tpu.models.decoder import stage_forward

    prompt = np.asarray([[3, 14, 15, 92], [7, 6, 5, 4]])
    res = engine.generate(prompt, 6, logprobs=True)
    assert res.logprobs is not None and res.logprobs.shape == (2, 6)
    assert (res.logprobs <= 0).all()
    # tokens unchanged by the flag
    base = engine.generate(prompt, 6)
    np.testing.assert_array_equal(base.tokens, res.tokens)
    assert base.logprobs is None
    # re-score: logprob of token t must match log_softmax at its position
    full = np.concatenate([prompt, res.tokens], axis=1)
    cache = KVCache.create(engine.cfg, engine.cfg.num_layers, 2,
                           full.shape[1])
    pos = jnp.broadcast_to(jnp.arange(full.shape[1]), full.shape)
    logits, _ = stage_forward(engine.params, engine.cfg,
                              StageSpec(0, 1, 0, engine.cfg.num_layers),
                              jnp.asarray(full), cache, pos)
    lsm = np.asarray(jax.nn.log_softmax(
        np.asarray(logits, np.float32), axis=-1))
    plen = prompt.shape[1]
    for b in range(2):
        for t in range(6):
            want = lsm[b, plen + t - 1, res.tokens[b, t]]
            np.testing.assert_allclose(res.logprobs[b, t], want, atol=5e-4)


@pytest.mark.slow
def test_eos_padding_in_fused_scan(engine):
    """Once a row emits eos_id, the fused scan pads its remaining steps
    with eos (mirrors the streaming path's early stop, row-wise)."""
    prompt = np.asarray([[3, 14, 15, 92]])
    first = engine.generate(prompt, 1).tokens[0, 0]
    eos_engine = InferenceEngine(engine.cfg, engine.params, max_seq=64,
                                 sampling=SamplingParams(greedy=True),
                                 eos_id=int(first))
    toks = eos_engine.generate(prompt, 8).tokens[0]
    assert (toks == int(first)).all()
    # and a non-eos run is unaffected by the flag
    other = InferenceEngine(engine.cfg, engine.params, max_seq=64,
                            sampling=SamplingParams(greedy=True),
                            eos_id=999999 % engine.cfg.vocab_size)
    base = engine.generate(prompt, 8).tokens
    if not (base == 999999 % engine.cfg.vocab_size).any():
        np.testing.assert_array_equal(other.generate(prompt, 8).tokens,
                                      base)


def test_eos_stream_matches_fused_scan_batch2(engine):
    """With eos_id set and batch > 1, the streamed and fused paths must
    still emit identical tokens (finished rows pad with eos in both)."""
    prompt = np.asarray([[3, 14, 15, 92], [8, 1, 9, 2]])
    first_row0 = int(engine.generate(prompt, 1).tokens[0, 0])
    eng = InferenceEngine(engine.cfg, engine.params, max_seq=64,
                          sampling=SamplingParams(greedy=True),
                          eos_id=first_row0)
    fused = eng.generate(prompt, 8).tokens
    streamed = np.stack(list(eng.generate_stream(prompt, 8, seed=0)), 1)
    np.testing.assert_array_equal(fused[:, :streamed.shape[1]], streamed)
    assert (fused[0] == first_row0).all()


def test_capacity_guard(engine):
    prompt = np.zeros((1, 60), np.int64)
    with pytest.raises(ValueError, match="exceeds KV-cache capacity"):
        engine.generate(prompt, max_new_tokens=10)


def test_eos_early_stop():
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    eng = InferenceEngine(cfg, params, max_seq=64,
                          sampling=SamplingParams(greedy=True))
    prompt = np.asarray([[1, 2, 3]])
    # find what greedy emits first, then declare it EOS: stream must stop at 1
    first = next(iter(eng.generate_stream(prompt, 4, seed=0)))
    eng.eos_id = int(first[0])
    toks = list(eng.generate_stream(prompt, 8, seed=0))
    assert len(toks) == 1


def test_attn_backend_flash_interpret_parity():
    """Engine-level wiring of the Pallas attention backend: the
    'flash-interpret' engine must generate identical tokens to 'jnp'."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 16))
    toks = {}
    for backend in ("jnp", "flash-interpret"):
        eng = InferenceEngine(cfg, params, max_seq=32,
                              sampling=SamplingParams(greedy=True),
                              attn_backend=backend)
        toks[backend] = eng.generate(prompt, 8, seed=0).tokens
    np.testing.assert_array_equal(toks["jnp"], toks["flash-interpret"])


def test_flash_accepts_misaligned_max_seq():
    """A max_seq that is NOT a multiple of 8 must still work on the flash
    backend: the engine pads the cache BUFFER to the sublane granule
    (models/base.pad_cache_capacity) while check_capacity keeps enforcing
    the caller's bound.  Regression: the r04 bench speculative leg died
    with 'flash attention requires max_seq divisible by 8, got 197'."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    prompt = np.random.RandomState(0).randint(0, cfg.vocab_size, (2, 11))
    toks = {}
    for backend in ("jnp", "flash-interpret"):
        eng = InferenceEngine(cfg, params, max_seq=27,
                              sampling=SamplingParams(greedy=True),
                              attn_backend=backend)
        assert eng.new_cache(2).max_seq == 32     # padded buffer
        toks[backend] = eng.generate(prompt, 8, seed=0).tokens
        with pytest.raises(ValueError, match="exceeds KV-cache capacity"):
            eng.generate(prompt, 17, seed=0)      # 11+17 > 27 still rejected
    np.testing.assert_array_equal(toks["jnp"], toks["flash-interpret"])


def test_chunked_prefill_misaligned_max_seq(engine):
    """Chunked prefill x non-multiple-of-8 max_seq: the left-shifted final
    chunk must WRITE at the shifted offset explicitly.  With the buffer
    padded past max_seq (27 -> 32) the old implicit dynamic_update_slice
    clamp lands at 32-8=24 instead of start=19, scattering the last
    chunk's K/V to the wrong columns — this pins the explicit
    length=start rewind in _run_prefill (engine.py)."""
    cfg = engine.cfg
    whole = InferenceEngine(cfg, engine.params, max_seq=27,
                            sampling=SamplingParams(greedy=True))
    chunked = InferenceEngine(cfg, engine.params, max_seq=27,
                              sampling=SamplingParams(greedy=True),
                              prefill_chunk=8)
    prompt = (np.arange(2 * 25).reshape(2, 25) % 199).astype(np.int32)
    want = whole.generate(prompt, 2).tokens
    got = chunked.generate(prompt, 2).tokens
    np.testing.assert_array_equal(want, got)


def test_attn_backend_rejects_unknown():
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="attn_backend"):
        InferenceEngine(cfg, params, attn_backend="pallas")


def test_fp8_kv_cache():
    """Opt-in reduced-precision cache: half the cache bytes, f32 attention
    math on upcast values, logits that track the full-precision cache."""
    import jax.numpy as jnp

    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    full = InferenceEngine(cfg, params, max_seq=64,
                           sampling=SamplingParams(greedy=True))
    fp8 = InferenceEngine(cfg, params, max_seq=64,
                          sampling=SamplingParams(greedy=True),
                          kv_cache_dtype="float8_e4m3fn")
    cache = fp8.new_cache(2)
    assert cache.keys.dtype == jnp.float8_e4m3fn
    assert cache.keys.nbytes * 4 == full.new_cache(2).keys.nbytes  # vs f32

    prompt = np.asarray(
        np.random.RandomState(11).randint(0, cfg.vocab_size, (2, 8)),
        np.int32)
    l_full, _ = full._prefill(full.params, prompt, full.new_cache(2))
    l_fp8, _ = fp8._prefill(fp8.params, prompt, fp8.new_cache(2))
    a, b = np.asarray(l_full, np.float64), np.asarray(l_fp8, np.float64)
    # prefill logits stay directionally faithful (cosine per row)
    cos = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1)
                             * np.linalg.norm(b, axis=-1))
    assert (cos > 0.98).all(), cos

    res = fp8.generate(prompt, 8)
    assert res.tokens.shape == (2, 8)
    assert ((res.tokens >= 0) & (res.tokens < cfg.vocab_size)).all()


def test_fp8_kv_cache_rejects_explicit_flash():
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    with pytest.raises(ValueError, match="incompatible"):
        InferenceEngine(cfg, params, max_seq=64, attn_backend="flash",
                        kv_cache_dtype="float8_e4m3fn")


def test_eos_stream_logprobs_match_fused(engine):
    """(token, logprob) pairs from the stream must match the fused scan
    even on eos-padded rows (mask-then-score order is shared)."""
    prompt = np.asarray([[3, 14, 15, 92], [8, 1, 9, 2]])
    first_row0 = int(engine.generate(prompt, 1).tokens[0, 0])
    eng = InferenceEngine(engine.cfg, engine.params, max_seq=64,
                          sampling=SamplingParams(greedy=True),
                          eos_id=first_row0)
    fused = eng.generate(prompt, 6, logprobs=True)
    pairs = list(eng.generate_stream(prompt, 6, logprobs=True))
    toks = np.stack([t for t, _ in pairs], 1)
    lps = np.stack([l for _, l in pairs], 1)
    n = toks.shape[1]
    np.testing.assert_array_equal(fused.tokens[:, :n], toks)
    np.testing.assert_allclose(fused.logprobs[:, :n], lps, atol=1e-5)
