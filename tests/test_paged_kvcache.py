"""PagedKVCacheManager (runtime/kvcache/paged.py): id-only bookkeeping
for the device page pool — allocation/eviction under pressure, lease
pinning, copy-free store adoption, and the accounting invariants the
block-leak engine tests rely on."""

import sys
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from distributed_inference_demo_tpu.runtime.kvcache import (
    PagedKVCacheManager, resolve_kv_layout)


def mgr(blocks=16, bt=4):
    return PagedKVCacheManager(num_layers=2, num_kv_heads=2, head_dim=4,
                               num_blocks=blocks, block_tokens=bt,
                               dtype=np.float32)


def test_alloc_free_accounting():
    m = mgr(8)
    ids = m.alloc(5)
    assert len(ids) == 5 and len(set(ids)) == 5
    assert m.used_blocks == 5 and m.free_blocks == 3
    m.free(ids[:2])
    assert m.used_blocks == 3
    with pytest.raises(RuntimeError):
        m.free(list(range(8)))    # over capacity = double free


def test_alloc_exhausted_returns_none_keeps_state():
    m = mgr(4)
    ids = m.alloc(4)
    assert m.alloc(1) is None     # nothing evictable: all request-owned
    assert m.used_blocks == 4
    m.free(ids)
    assert m.used_blocks == 0


def test_store_adopts_only_missing_blocks_and_match_hits():
    m = mgr(16, bt=4)
    prompt = np.arange(12)        # 3 full blocks
    mine = m.alloc(3)
    adopted, lease = m.store_shared(prompt, mine)
    assert list(adopted) == mine  # empty tree: everything adopted
    assert lease is not None and m.tree.block_count == 3

    # same prompt from a second request: nothing new to adopt
    theirs = m.alloc(3)
    adopted2, lease2 = m.store_shared(prompt, theirs)
    assert list(adopted2) == []
    # match returns the shared ids (capped below the prompt length)
    hit = m.match(np.arange(13))
    assert hit is not None and hit.block_ids == mine
    assert hit.tokens == 12
    hit.release()
    lease.release()
    lease2.release()
    m.free(theirs)                # not adopted: still request-owned
    assert m.used_blocks == m.tree.block_count == 3


def test_eviction_respects_lease_pins():
    m = mgr(6, bt=4)
    a = m.alloc(2)
    m.store_shared(np.arange(8), a)[1].release()          # tree: blocks 0-1
    b = m.alloc(2)
    lease_b = m.store_shared(np.arange(100, 108), b)[1]   # tree: pinned
    assert m.used_blocks == 4
    # pool has 2 free; asking for 4 must evict the UNPINNED leaf only
    got = m.alloc(4)
    assert got is not None
    assert m.stats["evicted_blocks"] == 2
    # the pinned node survived
    assert m.peek(np.arange(100, 109)) == 8
    lease_b.release()
    m.free(got)


def test_match_caps_below_prompt_len_and_counts():
    m = mgr(8, bt=4)
    ids = m.alloc(2)
    m.store_shared(np.arange(8), ids)[1].release()
    assert m.match(np.arange(4)) is None       # would cover whole prompt
    assert m.stats["misses"] == 0              # not even a lookup
    assert m.match(np.arange(200, 206)) is None  # real lookup, no match
    assert m.stats["misses"] == 1
    hit = m.match(np.arange(8))                # capped at 1 block
    assert hit.tokens == 4
    hit.release()
    snap = m.snapshot()
    assert snap["h2d_bytes"] == 0              # structural: no data here
    assert snap["device_resident_bytes"] == 2 * m.block_bytes
    assert snap["blocks_used"] == 2


def test_epoch_bumps_on_store_and_evict():
    m = mgr(4, bt=4)
    e0 = m.epoch
    ids = m.alloc(1)
    m.store_shared(np.arange(4, dtype=np.int64) + 50, ids)[1].release()
    assert m.epoch > e0
    e1 = m.epoch
    m.alloc(4)                                  # forces eviction
    assert m.epoch > e1


def test_layout_resolution_and_rejection(monkeypatch):
    # paged is the ONLY layout (docs/DESIGN.md §14); the removed dense
    # escape hatch fails loudly NAMING the removal, whichever door it
    # arrives through (kwarg or env — both funnel here)
    assert resolve_kv_layout(None) == "paged"
    assert resolve_kv_layout("paged") == "paged"
    with pytest.raises(ValueError, match="REMOVED"):
        resolve_kv_layout("dense")
    with pytest.raises(ValueError, match="unknown kv layout"):
        resolve_kv_layout("sparse")
    monkeypatch.setenv("DWT_KV_LAYOUT", "dense")
    with pytest.raises(ValueError, match="REMOVED"):
        resolve_kv_layout(None)
    monkeypatch.setenv("DWT_KV_LAYOUT", "paged")
    assert resolve_kv_layout(None) == "paged"


def test_infeasible_alloc_does_not_flush_the_cache():
    """Feasibility is checked before eviction: an admission that can
    never be satisfied must not evict a single tree leaf on its way to
    None (a pending request would otherwise flush the whole prefix
    cache once per scheduler retry)."""
    m = mgr(4, bt=4)
    ids = m.alloc(2)
    m.store_shared(np.arange(8), ids)[1].release()
    assert m.tree.block_count == 2 and m.free_blocks == 2
    assert m.alloc(5) is None                  # > pool: infeasible
    assert m.tree.block_count == 2             # nothing evicted
    assert m.stats["evicted_blocks"] == 0
    # pinned blocks are not reclaimable either
    hold = m.match(np.arange(9))
    assert m.alloc(3) is None                  # 2 free + 0 reclaimable
    assert m.tree.block_count == 2
    hold.release()
    got = m.alloc(3)                           # now feasible: evicts
    assert got is not None and m.stats["evicted_blocks"] == 2
    m.free(got)


def test_catalog_bridges_tree_share_vs_all_owners():
    """dwt_kvcache_used_blocks (tree share) and
    dwt_kvcache_blocks_in_use (all owners) must come from different
    snapshot keys on the paged layout — their gap is the §11 runbook's
    page-leak signal."""
    from distributed_inference_demo_tpu.telemetry import catalog
    m = mgr(8, bt=4)
    ids = m.alloc(2)
    lease = m.store_shared(np.arange(8), ids)[1]
    private = m.alloc(3)                       # in-flight request pages
    catalog.update_kvcache_series(m.snapshot())

    def val(metric):
        [(_, _, v)] = list(metric.samples())
        return v

    assert val(catalog.KVCACHE_USED_BLOCKS) == 2
    assert val(catalog.KVCACHE_BLOCKS_IN_USE) == 5
    assert val(catalog.KVCACHE_DEVICE_RESIDENT_BYTES) == 5 * m.block_bytes
    lease.release()
    m.free(private)
