"""Quantized KV pages (docs/DESIGN.md §17): int8 / packed int4 as
first-class page widths behind the kvcache seam.

The contract under test, layer by layer:

- ops: quantize→dequantize error is bounded by the per-token scale,
  re-quantizing a dequantized page is BIT-IDEMPOTENT (the invariant
  that lets a prefix-hit export re-quantize without drift), and the
  paged gather path over a quantized pool equals the dense reference
  over the pool's dequantized linearization bit-for-bit;
- kernel: the int8 Pallas kernel (interpret mode on CPU) matches the
  XLA gather fallback to f32 tolerance; int4 is deliberately gated off
  the kernel (nibble unpack is Mosaic-hostile) and says so loudly;
- seams: the byte budget admits blocks at their ACTUAL narrow width
  (satellite: the old full-width math undercounted capacity 2-4x),
  ``kv_dtype`` refuses to compose with the ``kv_cache_dtype`` storage
  cast, and snapshots/telemetry surface the page width;
- engines: greedy decode through quantized pools stays within pinned
  per-dtype agreement of the bf16 reference — cold runs on the plain
  engine are IDENTICAL (the prefix pool is untouched), primed runs are
  bounded; the batching scheduler decodes directly against quantized
  pages;
- disagg: a quantized migration payload adopts into the decode pool
  BIT-IDENTICALLY (narrow bytes + scale sidecar over the wire, verbatim
  scatter on adopt).
"""

import sys
import threading
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.attention import attention
from distributed_inference_demo_tpu.ops.paged_attention import (
    paged_flash_attention, paged_gather_attention, write_paged_kv)
from distributed_inference_demo_tpu.ops.quant import (
    KV_DTYPES, QuantizedKVPages, alloc_kv_pages, kv_scale_token_head_bytes,
    kv_token_head_bytes, quantize_kv_pages, resolve_kv_dtype)
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)

# empirically pinned greedy token-agreement floors for the tiny random
# llama-test model (primed plain engine / batching decode vs bf16) —
# regressions in the quantization math show up as drops below these
AGREEMENT_FLOOR = {"int8": 0.9, "int4": 0.6}


def _bits(kv_dtype):
    return 4 if kv_dtype == "int4" else 8


def _agreement(got, want):
    got, want = np.asarray(got).ravel(), np.asarray(want).ravel()
    n = min(len(got), len(want))
    return float((got[:n] == want[:n]).mean()) if n else 1.0


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def oracle(params):
    eng = InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY)

    def run(prompt, n):
        return eng.generate(np.asarray(prompt, np.int32)[None], n).tokens[0]
    return run


# ---------------------------------------------------------------------------
# ops: quantize / dequantize / paged paths


@pytest.mark.quick
@pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
def test_roundtrip_error_bounded_by_scale(kv_dtype):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 2, 8, 16)) * 3, jnp.float32)
    q = quantize_kv_pages(x, _bits(kv_dtype))
    err = np.abs(np.asarray(q.dequantize()) - np.asarray(x))
    # per-token bound: half a quantization step (+ float slack)
    bound = np.asarray(q.scale) * 0.5 + 1e-5
    assert (err <= bound).all(), float((err - bound).max())
    assert q.shape == x.shape and q.ndim == x.ndim
    assert q.nbytes < x.nbytes


@pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
def test_requantize_is_bit_idempotent(kv_dtype):
    """quantize(dequantize(q)) == q bitwise — the property that makes a
    prefix-hit re-export (disagg seed → export) drift-free."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((3, 2, 8, 16)), jnp.float32)
    q = quantize_kv_pages(x, _bits(kv_dtype))
    q2 = quantize_kv_pages(q.dequantize(), _bits(kv_dtype))
    np.testing.assert_array_equal(np.asarray(q.data), np.asarray(q2.data))
    np.testing.assert_allclose(np.asarray(q.scale), np.asarray(q2.scale),
                               rtol=1e-6)


@pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
def test_gather_matches_dense_on_dequantized(kv_dtype):
    """Paged attention over quantized pages == dense attention over the
    pool's dequantized linearization, bit-for-bit (the gather dequants
    then runs the exact same elementwise program)."""
    rng = np.random.default_rng(2)
    nkv, nh, hd, bt, W = 2, 4, 16, 8, 4
    lens = [5, 8, 17]
    b = len(lens)
    N = sum(-(-l // bt) for l in lens) + 2
    pk = quantize_kv_pages(
        jnp.asarray(rng.standard_normal((N, nkv, bt, hd)), jnp.float32),
        _bits(kv_dtype))
    pv = quantize_kv_pages(
        jnp.asarray(rng.standard_normal((N, nkv, bt, hd)), jnp.float32),
        _bits(kv_dtype))
    tables = np.full((b, W), N + 7, np.int32)
    nxt = 0
    for i, l in enumerate(lens):
        for j in range(-(-l // bt)):
            tables[i, j] = nxt
            nxt += 1
    tables = jnp.asarray(tables)
    q = jnp.asarray(rng.standard_normal((b, 1, nh, hd)), jnp.float32)
    qpos = jnp.asarray([l - 1 for l in lens], jnp.int32)[:, None]

    dk, dv = np.asarray(pk.dequantize()), np.asarray(pv.dequantize())
    k_lin = np.zeros((b, nkv, W * bt, hd), np.float32)
    v_lin = np.zeros_like(k_lin)
    tt = np.asarray(tables)
    for i in range(b):
        for j in range(W):
            if tt[i, j] < N:
                k_lin[i, :, j * bt:(j + 1) * bt] = dk[tt[i, j]]
                v_lin[i, :, j * bt:(j + 1) * bt] = dv[tt[i, j]]
    ref = attention(q, jnp.asarray(k_lin), jnp.asarray(v_lin), qpos,
                    jnp.int32(W * bt), None)
    got = paged_gather_attention(q, pk, pv, tables, qpos, None)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    # the int8 kernel (interpret) against the gather oracle; int4 is
    # gated off the kernel and must say so
    kv_lens = jnp.asarray(lens, jnp.int32)
    if kv_dtype == "int8":
        out = paged_flash_attention(q, pk, pv, tables, kv_lens, None,
                                    interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(got),
                                   rtol=2e-5, atol=2e-5)
    else:
        with pytest.raises(ValueError, match="int4"):
            paged_flash_attention(q, pk, pv, tables, kv_lens, None,
                                  interpret=True)


@pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
def test_write_quantizes_at_the_page_boundary(kv_dtype):
    """write_paged_kv into a quantized pool quantizes ONCE, landing the
    same bytes a direct quantize of the chunk produces, at the right
    page/offset; sentinel writes still vanish."""
    rng = np.random.default_rng(3)
    nkv, hd, bt, W = 2, 16, 8, 3
    N, b = 6, 2
    pk = alloc_kv_pages((N, nkv, bt, hd), kv_dtype, jnp.float32)
    pv = jax.tree.map(jnp.zeros_like, pk)
    tables = jnp.asarray([[0, 1, 2], [3, 4, N + 7]], jnp.int32)
    k_new = jnp.asarray(rng.standard_normal((b, 1, nkv, hd)), jnp.float32)
    v_new = jnp.asarray(rng.standard_normal((b, 1, nkv, hd)), jnp.float32)
    pos = jnp.asarray([[9], [3]], jnp.int32)
    pk2, pv2 = write_paged_kv(pk, pv, k_new, v_new, tables, pos)
    qk = quantize_kv_pages(k_new, _bits(kv_dtype))
    # row 0 lands page 1 offset 1; row 1 page 3 offset 3
    np.testing.assert_array_equal(np.asarray(pk2.data)[1, :, 1],
                                  np.asarray(qk.data)[0, 0])
    np.testing.assert_array_equal(np.asarray(pk2.scale)[1, :, 1],
                                  np.asarray(qk.scale)[0, 0])
    np.testing.assert_array_equal(np.asarray(pk2.data)[3, :, 3],
                                  np.asarray(qk.data)[1, 0])
    # a sentinel table entry drops the write: no page changed for a
    # row routed entirely through the sentinel
    all_sent = jnp.full_like(tables, N + 7)
    pk3, pv3 = write_paged_kv(pk2, pv2, k_new, v_new, all_sent, pos)
    np.testing.assert_array_equal(np.asarray(pk3.data),
                                  np.asarray(pk2.data))
    np.testing.assert_array_equal(np.asarray(pv3.scale),
                                  np.asarray(pv2.scale))


@pytest.mark.quick
def test_byte_owners_and_resolver(monkeypatch):
    """kv_token_head_bytes is the ONE owner of page-width math: narrow
    data + scale sidecar, ~2x / ~4x under bf16 at real head dims."""
    bf16 = kv_token_head_bytes(128, "bf16", jnp.bfloat16)
    i8 = kv_token_head_bytes(128, "int8", jnp.bfloat16)
    i4 = kv_token_head_bytes(128, "int4", jnp.bfloat16)
    assert (bf16, i8, i4) == (256, 128 + 4, 64 + 8)
    assert [kv_scale_token_head_bytes(d) for d in KV_DTYPES] == [0, 4, 8]
    with pytest.raises(ValueError):
        kv_token_head_bytes(128, "int2", jnp.bfloat16)
    with pytest.raises(ValueError):
        quantize_kv_pages(jnp.zeros((2, 3)), 4)  # odd head_dim unpackable

    assert resolve_kv_dtype("int8") == "int8"
    monkeypatch.setenv("DWT_KV_DTYPE", "int4")
    assert resolve_kv_dtype(None) == "int4"
    assert resolve_kv_dtype("bf16") == "bf16"  # arg wins over env
    monkeypatch.setenv("DWT_KV_DTYPE", "fp7")
    with pytest.raises(ValueError, match="fp7"):
        resolve_kv_dtype(None)


# ---------------------------------------------------------------------------
# seams: byte budget, exclusivity, snapshot/telemetry


def test_byte_budget_admits_more_narrow_blocks(monkeypatch):
    """The make_kv_backend byte ceiling counts blocks at their ACTUAL
    width: at a fixed DWT_KVCACHE_BYTES budget an int8 pool holds ~2x
    the bf16 block count, int4 ~4x (the satellite fix: the old math
    priced every width at the full itemsize)."""
    from distributed_inference_demo_tpu.runtime.kvcache import (
        make_kv_backend)
    bf16_block = (2 * CFG.num_layers * CFG.num_kv_heads * 8
                  * kv_token_head_bytes(CFG.head_dim, "bf16", CFG.dtype))
    monkeypatch.setenv("DWT_KVCACHE_BYTES", str(4 * bf16_block))
    n = {}
    for d in KV_DTYPES:
        be = make_kv_backend(CFG, 64, 8, layout="paged", kv_dtype=d)
        n[d] = be.mgr.num_blocks
        assert be.kv_dtype == d
    assert n["bf16"] == 4
    assert n["int8"] > n["bf16"]
    assert n["int4"] > n["int8"]


def test_kv_dtype_refuses_storage_cast(params):
    from distributed_inference_demo_tpu.runtime.kvcache import (
        make_kv_backend)
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        make_kv_backend(CFG, 8, 8, layout="paged",
                        dtype=jnp.dtype("float16"), kv_dtype="int8")
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        ContinuousBatchingEngine(CFG, params, max_seq=64, max_batch=1,
                                 kv_cache_dtype="float16",
                                 kv_dtype="int8")


def test_snapshot_and_metrics_surface_page_dtype():
    from distributed_inference_demo_tpu.runtime.kvcache import (
        PagedKVCacheManager)
    from distributed_inference_demo_tpu.telemetry import catalog
    from distributed_inference_demo_tpu.telemetry.metrics import REGISTRY
    mgr = PagedKVCacheManager.for_model(CFG, 8, 8, kv_dtype="int4")
    snap = mgr.snapshot()
    assert snap["page_dtype"] == "int4"
    assert snap["quant_scale_bytes"] == 0          # idle pool
    ids = mgr.alloc(3)
    snap = mgr.snapshot()
    assert snap["quant_scale_bytes"] == 3 * mgr.scale_block_bytes > 0
    assert snap["page_dtype"] in dict(mgr.debug_state()).values()
    catalog.update_kvcache_series(snap)
    text = REGISTRY.render()
    assert 'dwt_kvcache_page_dtype_info{dtype="int4"} 1' in text
    assert "dwt_kvcache_quant_scale_bytes" in text
    mgr.free(ids)


# ---------------------------------------------------------------------------
# engines: greedy parity, cold and primed


@pytest.mark.parametrize("kv_dtype", ["int8", "int4"])
def test_plain_engine_cold_identical_primed_bounded(params, oracle,
                                                    kv_dtype):
    """Plain engine + quantized prefix pool: a COLD run never touches
    the pool, so its greedy tokens are IDENTICAL to bf16; the primed
    re-run decodes from dequantized pages and must stay within the
    pinned per-dtype agreement floor while actually hitting the radix
    tree (scales ride the block table through adoption)."""
    prompt = list((np.arange(19) % 29 + 2).astype(int))
    eng = InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY,
                          kv_cache_blocks=16, kv_block_tokens=8,
                          kv_dtype=kv_dtype)
    want = oracle(prompt, 12)
    cold = eng.generate(np.asarray(prompt, np.int32)[None], 12).tokens[0]
    np.testing.assert_array_equal(cold, want)
    snap = eng.kv_cache.snapshot()
    assert snap["page_dtype"] == kv_dtype
    assert snap["stored_blocks"] >= 2
    primed = eng.generate(np.asarray(prompt, np.int32)[None],
                          12).tokens[0]
    assert eng.kv_cache.snapshot()["hits"] >= 1
    agr = _agreement(primed, want)
    assert agr >= AGREEMENT_FLOOR[kv_dtype], (kv_dtype, agr, primed, want)


@pytest.fixture(scope="module")
def int8_batching(params):
    with ContinuousBatchingEngine(
            CFG, params, max_seq=96, max_batch=2, sampling=GREEDY,
            prompt_buckets=(16,), kv_block_tokens=8,
            kv_dtype="int8") as eng:
        yield eng


def test_batching_decodes_against_quantized_pages(int8_batching, oracle):
    """The scheduler's decode step reads K/V straight out of int8 pages
    (no dense shadow): greedy agreement with the bf16 reference stays
    above the pinned floor for every concurrent request, and the pool
    leak invariant holds with sidecars in play."""
    eng = int8_batching
    prompts = [[3, 14, 15, 9, 2, 6], [1, 7, 7, 21]]
    reqs = [eng.submit(p, 12) for p in prompts]
    for p, r in zip(prompts, reqs):
        agr = _agreement(r.wait(timeout=300), oracle(p, 12))
        assert agr >= AGREEMENT_FLOOR["int8"], (p, agr)
    mgr = eng.kv_cache
    assert mgr.used_blocks == mgr.tree.block_count
    assert mgr.snapshot()["page_dtype"] == "int8"


@pytest.mark.slow
def test_speculative_decodes_against_quantized_pages(params, oracle):
    """The speculative path inherits the quantized pool through the
    same make_kv_backend seam: a COLD greedy run never reads the pool
    (draft-verify exactness keeps it bit-identical to the plain bf16
    oracle), and the primed re-run seeds from dequantized int8 pages
    while holding the pinned agreement floor with real radix hits."""
    from distributed_inference_demo_tpu.runtime.speculative import (
        SpeculativeEngine)
    cfg8 = get_model_config("llama-test-int8")
    params8 = init_full_params(jax.random.PRNGKey(0), cfg8,
                               quantize=True)
    spec = SpeculativeEngine(CFG, params, cfg8, params8, max_seq=96,
                             sampling=GREEDY, num_draft=3,
                             kv_cache_blocks=16, kv_block_tokens=8,
                             kv_dtype="int8")
    prompt = list((np.arange(17) % 23 + 2).astype(int))
    want = oracle(prompt, 12)
    r1, _ = spec.generate(np.asarray(prompt, np.int32)[None], 12)
    np.testing.assert_array_equal(r1.tokens[0], want)
    assert spec.kv_cache.snapshot()["page_dtype"] == "int8"
    r2, _ = spec.generate(np.asarray(prompt, np.int32)[None], 12)
    assert spec.kv_cache.snapshot()["hits"] >= 1
    agr = _agreement(r2.tokens[0], want)
    assert agr >= AGREEMENT_FLOOR["int8"], (agr, r2.tokens, want)


def test_disagg_quantized_pages_adopt_bit_identically(params,
                                                      int8_batching):
    """The §15 join with int8 pages: blocks quantized ONCE at the
    prefill worker's export adopt into the decode pool VERBATIM — the
    decode-side page bytes and scale sidecars equal the exported
    payload exactly, zero H2D, and the joined request completes."""
    from distributed_inference_demo_tpu.comm.transport import (
        LoopbackNetwork, LoopbackTransport)
    from distributed_inference_demo_tpu.models.base import KVCache
    from distributed_inference_demo_tpu.runtime.disagg import PrefillWorker

    eng = int8_batching
    bt = eng.kv_cache.block_tokens
    net = LoopbackNetwork()
    pw = PrefillWorker(CFG, params, LoopbackTransport("pq", net),
                       max_seq=96, prefill_chunk=8, kv_block_tokens=bt,
                       kv_dtype="int8")
    assert pw.kv_cache.kv_dtype == "int8"
    prompt = (np.arange(33) % 43 + 2).astype(np.int32)
    n_mig = (len(prompt) - 1) // bt
    row = KVCache.create(CFG, CFG.num_layers, 1, 96)
    cache = KVCache(row.keys, row.values, jnp.int32(0))
    pos = 0
    while pos < n_mig * bt:
        step = min(8, n_mig * bt - pos)
        chunk = np.zeros((1, 8), np.int32)
        chunk[0, :step] = prompt[pos:pos + step]
        cache = pw._chunk_mid(pw.params, jnp.asarray(chunk), cache,
                              jnp.int32(pos))
        pos += step
    k, v = pw._export_blocks(cache.keys, cache.values, 0, n_mig)
    assert isinstance(k, QuantizedKVPages) and k.bits == 8

    req = eng.submit_premigrated(prompt, 6, k, v)
    out = req.wait(timeout=300)
    assert len(out) == 6
    snap = eng.kv_cache.snapshot()
    assert snap["h2d_bytes"] == 0

    # the adopted prefix pages hold EXACTLY the exported bytes
    lease = eng.kv_cache.match(prompt)
    assert lease is not None and lease.tokens >= n_mig * bt - bt
    ids = list(lease.block_ids)[:n_mig]
    pool_k, pool_v = eng._pk, eng._pv
    for i, b in enumerate(ids):
        np.testing.assert_array_equal(np.asarray(pool_k.data)[:, b],
                                      np.asarray(k.data)[i])
        np.testing.assert_array_equal(np.asarray(pool_k.scale)[:, b],
                                      np.asarray(k.scale)[i])
        np.testing.assert_array_equal(np.asarray(pool_v.data)[:, b],
                                      np.asarray(v.data)[i])
    lease.release()

    # a width mismatch is refused loudly, never silently dequantized
    with pytest.raises(ValueError, match="matching quantized pool"):
        from distributed_inference_demo_tpu.ops.quant import (
            quantize_kv_pages as qkp)
        bad_k = qkp(jnp.asarray(np.asarray(k.data, np.float32)
                                [..., : CFG.head_dim]), 4)
        eng.submit_premigrated(prompt, 4, bad_k, bad_k)


def test_page_frame_carries_quantized_leaves():
    """Wire format: quantized frames tag kv_dtype and carry the flat
    leaf list; bf16 frames keep the pre-§17 two-tensor format (byte
    compatibility with older senders)."""
    from distributed_inference_demo_tpu.runtime.disagg import (
        _page_frame, _parse_meta_frame)
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 3, 2, 4, 6)), jnp.float32)
    qk = jax.tree.map(np.asarray, quantize_kv_pages(x, 4))
    qv = jax.tree.map(np.asarray, quantize_kv_pages(-x, 4))
    meta, tensors, _ = _parse_meta_frame(_page_frame(qk, qv, 7))
    assert meta == {"first_block": 7, "n_blocks": 2, "kv_dtype": "int4"}
    assert len(tensors) == 6
    np.testing.assert_array_equal(tensors[0], qk.data)
    np.testing.assert_array_equal(tensors[1], qk.scale)
    np.testing.assert_array_equal(tensors[2], qk.zero)
    np.testing.assert_array_equal(tensors[3], qv.data)
    meta2, t2, _ = _parse_meta_frame(
        _page_frame(np.asarray(x), np.asarray(-x), 0))
    assert meta2 == {"first_block": 0, "n_blocks": 2}
    assert len(t2) == 2
