"""End-to-end telemetry over the two-stage pipeline (ISSUE 1 acceptance):
a generate run produces a Chrome trace export in which EVERY token has a
complete span chain (header send → worker compute → token return) with
non-negative, nested timestamps; the header's /metrics scrape returns
valid Prometheus text containing stage, batching, and monitor series;
and worker spans flow back over the statsreq control path exactly once.
"""

import json
import threading
import urllib.request

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import (
    slice_stage, split_layer_ranges)
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport)
from distributed_inference_demo_tpu.runtime.distributed import (
    PipelineHeader, PipelineWorker, StageRuntime)
from distributed_inference_demo_tpu.runtime.http_server import (
    HeaderBackend, InferenceHTTPServer)
from distributed_inference_demo_tpu.telemetry.tracing import (
    TraceRecorder, to_chrome_trace)

from test_metrics import parse_exposition

GREEDY = SamplingParams(greedy=True)
PROMPT = np.array([[5, 17, 42, 7, 99, 3, 12, 56]], dtype=np.int32)


def _build(num_stages=2, max_seq=64):
    cfg = get_model_config("llama-test")
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, num_stages)
    net = LoopbackNetwork()
    ids = [f"s{i}" for i in range(num_stages)]
    transports = [LoopbackTransport(d, net) for d in ids]
    header = PipelineHeader(
        StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                     max_seq, GREEDY),
        transports[0], next_id=ids[1], step_timeout=60)
    workers = []
    for i in range(1, num_stages):
        workers.append(PipelineWorker(
            StageRuntime(cfg, specs[i], slice_stage(full, cfg, specs[i]),
                         max_seq, GREEDY),
            transports[i],
            next_id=ids[i + 1] if i + 1 < num_stages else None,
            header_id=ids[0], step_timeout=60))
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    return header, workers, threads


def _events_by_step(trace, name, proc_prefix=None):
    """{step: event} for one span name (optionally one stage's)."""
    pid_names = {e["pid"]: e["args"]["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"}
    out = {}
    for e in trace["traceEvents"]:
        if e.get("ph") != "X" or e["name"] != name:
            continue
        if (proc_prefix is not None
                and not pid_names[e["pid"]].startswith(proc_prefix)):
            continue
        out[e["args"]["step"]] = e
    return out


@pytest.mark.slow
def test_e2e_trace_has_complete_span_chain_per_token():
    header, workers, threads = _build(num_stages=2)
    new = 5
    toks = header.generate(PROMPT, new)
    assert toks.shape == (1, new)

    trace = header.collect_trace(num_stages=2)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)

    # the export is valid JSON all the way down (Perfetto loads it)
    trace = json.loads(json.dumps(trace))
    assert trace["traceEvents"]

    sends = _events_by_step(trace, "send", proc_prefix="header:")
    computes = _events_by_step(trace, "compute", proc_prefix="tail:")
    rtts = _events_by_step(trace, "ring_rtt")
    waits = _events_by_step(trace, "recv_wait", proc_prefix="tail:")

    # every generated token: header send -> worker compute -> token back
    for step in range(new):
        assert step in sends, f"no header send span for step {step}"
        assert step in computes, f"no tail compute span for step {step}"
        assert step in rtts, f"no ring_rtt span for step {step}"
        s, c, r = sends[step], computes[step], rtts[step]
        # one trace id threads the whole chain
        assert (s["args"]["trace_id"] == c["args"]["trace_id"]
                == r["args"]["trace_id"])
        # non-negative timestamps/durations
        for e in (s, c, r):
            assert e["ts"] >= 0 and e["dur"] >= 0
        # nesting: the worker's compute happens inside the window the
        # header observed (send start .. rtt end); same-process clocks
        # make this exact on the loopback transport
        assert s["ts"] <= c["ts"], "compute started before the send"
        assert c["ts"] + c["dur"] <= r["ts"] + r["dur"] + 1, \
            "compute ended after the token came back"
        # parent chain: worker spans name the header's send span
        assert c["args"]["parent_span_id"] == s["args"]["span_id"]
        if step in waits:
            assert waits[step]["args"]["parent_span_id"] == \
                s["args"]["span_id"]

    # header prefill/decode computes are tagged too
    hdr_computes = _events_by_step(trace, "compute",
                                   proc_prefix="header:")
    assert hdr_computes[0]["args"]["phase"] == "prefill"

    # drained-once: a second collection has no span events left
    trace2 = header.collect_trace(num_stages=2)
    assert not [e for e in trace2["traceEvents"] if e.get("ph") == "X"]


def test_trace_ids_distinct_per_request():
    header, workers, threads = _build(num_stages=2)
    header.generate_many([PROMPT, PROMPT], 2, pool_size=2)
    trace = header.collect_trace(num_stages=2)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)
    ids = {e["args"]["trace_id"] for e in trace["traceEvents"]
           if e.get("ph") == "X"}
    assert len(ids) == 2


def test_http_metrics_and_trace_on_header():
    header, workers, threads = _build(num_stages=2)
    backend = HeaderBackend(header, max_seq=64, num_stages=2)
    srv = InferenceHTTPServer(backend, model_name="llama-test")
    srv.start()
    try:
        url = f"http://{srv.host}:{srv.port}"
        body = json.dumps({"prompt_ids": PROMPT.tolist(),
                           "max_new_tokens": 3}).encode()
        req = urllib.request.Request(url + "/generate", data=body,
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert json.loads(r.read())["tokens"]

        with urllib.request.urlopen(url + "/metrics", timeout=60) as r:
            text = r.read().decode()
        samples, types = parse_exposition(text)
        # stage series for BOTH pipeline roles, from the statsreq poll
        steps = {dict(lab).get("role"): v for (n, lab), v
                 in samples.items() if n == "dwt_stage_steps_total"}
        assert steps.get("header") == 3 and steps.get("tail") == 3
        recv = {dict(lab).get("role"): v for (n, lab), v
                in samples.items() if n == "dwt_stage_recv_bytes_total"}
        assert recv.get("tail", 0) > 0
        # batching + monitor series present (acceptance criterion)
        assert types.get("dwt_batching_queue_depth_requests") == "gauge"
        assert samples[("dwt_monitor_host_memory_bytes",
                        frozenset({("kind", "total")}))] > 0

        with urllib.request.urlopen(url + "/trace", timeout=60) as r:
            trace = json.loads(r.read())
        names = {e["name"] for e in trace["traceEvents"]
                 if e.get("ph") == "X"}
        assert {"send", "compute", "ring_rtt"} <= names
    finally:
        srv.shutdown()
        header.shutdown_pipeline()
        for t in threads:
            t.join(timeout=30)


def test_untraced_messages_still_served():
    """A hand-built untraced 'h' message (an old client) flows through a
    worker without trace context — backward compat on the serving path."""
    from distributed_inference_demo_tpu.comm import wire
    cfg = get_model_config("llama-test")
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    t0, t1 = LoopbackTransport("s0", net), LoopbackTransport("s1", net)
    worker = PipelineWorker(
        StageRuntime(cfg, specs[1], slice_stage(full, cfg, specs[1]),
                     64, GREEDY),
        t1, next_id=None, header_id="s0", step_timeout=60)
    hidden = np.zeros((1, 4, cfg.hidden_size), np.float32)
    worker.handle_message("h:0:0", wire.serialize_tensors([hidden]))
    tag, payload = t0.recv_any(timeout=30)
    assert tag == "tok:0:0"
    tensors, ctx = wire.split_trace_context(
        wire.deserialize_tensors(payload))
    assert ctx is None and tensors[0].shape == (1,)
    assert len(worker.tracer) == 0          # nothing recorded untraced


def test_trace_recorder_bounded_and_drains():
    rec = TraceRecorder("t", max_spans=4)
    for i in range(10):
        rec.record("x", trace_id=1, dur=0.001, step=i)
    assert len(rec) == 4
    spans = rec.drain()
    assert [s["args"]["step"] for s in spans] == [6, 7, 8, 9]
    assert rec.drain() == []
    chrome = to_chrome_trace(spans)
    assert len([e for e in chrome["traceEvents"]
                if e.get("ph") == "X"]) == 4


def test_span_clock_captures_wall_start_at_open():
    """The NTP-step fix: a span's start is the wall clock captured at
    span OPEN, never reconstructed as now-minus-duration at close."""
    import time as _time

    from distributed_inference_demo_tpu.telemetry.tracing import SpanClock

    before = _time.time()
    clk = SpanClock()
    after = _time.time()
    assert before <= clk.ts <= after
    _time.sleep(0.02)
    dur = clk.stop()
    assert dur >= 0.02
    assert clk.stop() == dur            # frozen after first read

    rec = TraceRecorder("t")
    rec.record("compute", trace_id=1, clock=clk)
    [span] = rec.snapshot()
    # recorded start == the OPEN capture, independent of record() time
    assert span["ts_us"] == int(clk.ts * 1e6)
    assert span["dur_us"] == int(dur * 1e6)


def test_record_without_ts_stamps_call_time_not_now_minus_dur():
    import time as _time

    rec = TraceRecorder("t")
    before = _time.time()
    rec.record("x", trace_id=1, dur=5.0)    # no ts: stamped at call time
    after = _time.time()
    [span] = rec.snapshot()
    assert int(before * 1e6) <= span["ts_us"] <= int(after * 1e6)


def test_runlog_rollover_at_max_bytes(tmp_path):
    """Satellite: RunLog rolls to <path>.1 at the byte budget instead of
    growing without bound; the rotation boundary loses nothing."""
    from distributed_inference_demo_tpu.telemetry.runlog import RunLog

    path = tmp_path / "run.jsonl"
    rl = RunLog(str(path), run_id="r", max_bytes=400)
    for i in range(20):
        rl.event("tick", i=i)
    rl.close()
    rolled = tmp_path / "run.jsonl.1"
    assert rolled.exists(), "no rollover happened"
    assert path.stat().st_size <= 400
    assert rolled.stat().st_size <= 400
    # the boundary is clean: every surviving line parses whole (nothing
    # torn mid-rotation), and the two generations form one contiguous
    # tail ending at the newest event (older generations are dropped by
    # design — one spare bounds disk at 2 x max_bytes)
    events = []
    for p in (rolled, path):
        for line in p.read_text().splitlines():
            events.append(json.loads(line)["i"])
    assert events == list(range(events[0], 20))


def test_runlog_rollover_keeps_single_spare(tmp_path):
    from distributed_inference_demo_tpu.telemetry.runlog import RunLog

    path = tmp_path / "run.jsonl"
    rl = RunLog(str(path), max_bytes=200)
    for i in range(60):
        rl.event("tick", i=i)
    rl.close()
    files = sorted(p.name for p in tmp_path.iterdir())
    assert files == ["run.jsonl", "run.jsonl.1"]   # bounded: two files
    last = json.loads(path.read_text().splitlines()[-1])
    assert last["i"] == 59


def test_runlog_oversized_line_lands_in_fresh_file(tmp_path):
    from distributed_inference_demo_tpu.telemetry.runlog import RunLog

    path = tmp_path / "run.jsonl"
    rl = RunLog(str(path), max_bytes=100)
    rl.event("small")
    rl.event("big", blob="x" * 500)     # alone exceeds the whole budget
    rl.close()
    lines = path.read_text().splitlines()
    assert len(lines) == 1 and json.loads(lines[0])["event"] == "big"
    assert (tmp_path / "run.jsonl.1").exists()


def test_runlog_no_rollover_when_unset(tmp_path):
    from distributed_inference_demo_tpu.telemetry.runlog import RunLog

    path = tmp_path / "run.jsonl"
    rl = RunLog(str(path))              # max_bytes 0 = unbounded
    for i in range(50):
        rl.event("tick", i=i)
    rl.close()
    assert not (tmp_path / "run.jsonl.1").exists()
    assert len(path.read_text().splitlines()) == 50


# slow lane: on-header endpoint twin of test_http_metrics_and_trace_on_header
# (same gating plumbing); /debugz content is pinned in the fleet tests
@pytest.mark.slow
def test_http_debugz_on_header():
    """GET /debugz returns flight-ring state, backend in-flight info,
    and postmortem status without touching the pipeline."""
    from distributed_inference_demo_tpu.telemetry.flightrecorder import (
        set_flight_recorder)

    set_flight_recorder(None)
    header, workers, threads = _build(num_stages=2)
    backend = HeaderBackend(header, max_seq=64, num_stages=2)
    srv = InferenceHTTPServer(backend, model_name="llama-test")
    srv.start()
    try:
        url = f"http://{srv.host}:{srv.port}"
        body = json.dumps({"prompt_ids": PROMPT.tolist(),
                           "max_new_tokens": 2}).encode()
        req = urllib.request.Request(url + "/generate", data=body,
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert json.loads(r.read())["tokens"]
        with urllib.request.urlopen(url + "/debugz", timeout=60) as r:
            dz = json.loads(r.read())
        assert dz["flight"]["total"] > 0
        kinds = {e["kind"] for e in dz["flight"]["tail"]}
        assert {"hop_send", "tok_recv"} <= kinds
        assert dz["backend"]["num_stages"] == 2
        assert dz["backend"]["in_flight"] == []
        assert dz["postmortem"]["dir"] is None     # capture unconfigured
    finally:
        srv.shutdown()
        header.shutdown_pipeline()
        for t in threads:
            t.join(timeout=30)
        set_flight_recorder(None)
