"""Anomaly detectors under a fake clock: deterministic straggler, stall,
and SLO-breach scenarios each fire exactly once (sustain + cooldown — no
duplicate-trigger storms), and each produces exactly one postmortem
bundle when a writer is configured."""

import os

import pytest

from distributed_inference_demo_tpu.telemetry import postmortem
from distributed_inference_demo_tpu.telemetry.anomaly import (
    Anomaly, AnomalyDetector, AnomalyMonitor, Thresholds)
from distributed_inference_demo_tpu.telemetry.flightrecorder import (
    set_flight_recorder)


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(autouse=True)
def _isolate_globals():
    set_flight_recorder(None)
    postmortem.set_postmortem_writer(None)
    os.environ.pop("DWT_POSTMORTEM_DIR", None)
    yield
    set_flight_recorder(None)
    postmortem.set_postmortem_writer(None)


TH = Thresholds(straggler_factor=3.0, straggler_min_ms=1.0,
                ttft_slo_ms=100.0, tpot_slo_ms=50.0, queue_depth=16,
                accept_floor=0.2, accept_min_drafted=40, stall_s=30.0,
                sustain=3, cooldown_s=300.0)


def _stages(slow_ms: float):
    return [{"role": "header", "device_id": "h", "compute_p95_ms": 2.0},
            {"role": "worker", "device_id": "w1", "compute_p95_ms": 2.0},
            {"role": "tail", "device_id": "w2",
             "compute_p95_ms": slow_ms}]


def test_straggler_fires_once_then_cooldown():
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    fired = []
    for _ in range(10):
        fired += det.observe({"stages": _stages(20.0)})
        clock.advance(1.0)
    assert len(fired) == 1                       # sustain=3, cooldown eats
    [a] = fired                                  # the other 7 breaches
    assert a.kind == "straggler_hop"
    assert a.detail["device"] == "w2"
    assert a.detail["compute_p95_ms"] == 20.0
    # past the cooldown, a persisting straggler may fire again
    clock.advance(400.0)
    assert len(det.observe({"stages": _stages(20.0)})) == 1


def test_straggler_fires_in_two_stage_ring():
    """The default topology (header + one worker): the baseline is the
    OTHER stage's p95, so a 2-stage ring's straggler can fire (a ring
    median over all stages would be the straggler itself and never
    could)."""
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    two = [{"role": "header", "device_id": "h", "compute_p95_ms": 2.0},
           {"role": "tail", "device_id": "w1", "compute_p95_ms": 40.0}]
    fired = []
    for _ in range(5):
        fired += det.observe({"stages": two})
        clock.advance(1.0)
    assert [a.kind for a in fired] == ["straggler_hop"]
    assert fired[0].detail["device"] == "w1"
    assert fired[0].detail["ring_median_ms"] == 2.0


def test_straggler_streak_resets_on_recovery():
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    assert det.observe({"stages": _stages(20.0)}) == []
    assert det.observe({"stages": _stages(20.0)}) == []
    assert det.observe({"stages": _stages(2.0)}) == []   # recovered
    assert det.observe({"stages": _stages(20.0)}) == []  # streak restarted
    assert det.observe({"stages": _stages(20.0)}) == []


def test_slo_breach_fires_once():
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    stats = {"steps": 1, "latency": {"ttft_p95_ms": 250.0}}
    fired = []
    for _ in range(8):
        stats = dict(stats, steps=stats["steps"] + 1)  # no stall noise
        fired += det.observe(stats)
        clock.advance(1.0)
    assert [a.kind for a in fired] == ["slo_ttft"]
    assert fired[0].detail == {"ttft_p95_ms": 250.0, "slo_ms": 100.0}


def test_slo_disabled_when_zero():
    det = AnomalyDetector(Thresholds(ttft_slo_ms=0.0, sustain=1),
                          clock=FakeClock())
    assert det.observe({"latency": {"ttft_p95_ms": 9999.0}}) == []


def test_stall_watchdog_fires_once_per_window():
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    stats = {"steps": 42, "active_slots": 3, "queue_depth": 0}
    assert det.observe(stats) == []              # baseline observation
    clock.advance(29.0)
    assert det.observe(stats) == []              # inside the window
    clock.advance(2.0)                           # 31 s frozen: fire
    [a] = det.observe(stats)
    assert a.kind == "pipeline_stall"
    assert a.detail["steps"] == 42
    assert a.detail["stalled_for_s"] >= 30.0
    clock.advance(10.0)
    assert det.observe(stats) == []              # cooldown: no storm
    # progress resumes, then a NEW stall past the cooldown fires again
    assert det.observe(dict(stats, steps=43)) == []
    clock.advance(400.0)
    [b] = det.observe(dict(stats, steps=43))
    assert b.kind == "pipeline_stall"


def test_stall_needs_work_in_flight():
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    idle = {"steps": 42, "active_slots": 0, "queue_depth": 0}
    det.observe(idle)
    clock.advance(1000.0)
    assert det.observe(idle) == []               # idle != stalled


def test_stall_window_restarts_after_idle_period():
    """Idle-then-resume must NOT fire instantly: the frozen-steps window
    starts when work arrives, not when the engine last stepped."""
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    det.observe({"steps": 42, "active_slots": 0, "queue_depth": 0})
    clock.advance(600.0)                         # long idle stretch
    det.observe({"steps": 42, "active_slots": 0, "queue_depth": 0})
    clock.advance(1.0)
    busy = {"steps": 42, "active_slots": 1, "queue_depth": 0}
    assert det.observe(busy) == []               # healthy resume
    clock.advance(10.0)
    assert det.observe(busy) == []               # still inside window
    clock.advance(25.0)                          # NOW 35s busy-frozen
    [a] = det.observe(busy)
    assert a.kind == "pipeline_stall"
    assert a.detail["stalled_for_s"] < 60.0      # not the stale 600s


def test_slo_streak_clears_when_metric_vanishes():
    """Sustain means CONSECUTIVE: a stats-reset gap (the p95 disappears)
    must restart the streak, not preserve two old breaches."""
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    breach = {"steps": 1, "latency": {"ttft_p95_ms": 250.0}}
    det.observe(dict(breach, steps=1))
    det.observe(dict(breach, steps=2))           # streak = 2
    det.observe({"steps": 3, "latency": {}})     # reservoir reset: gap
    assert det.observe(dict(breach, steps=4)) == []   # streak restarted
    assert det.observe(dict(breach, steps=5)) == []


def test_queue_saturation_and_accept_collapse():
    clock = FakeClock()
    det = AnomalyDetector(TH, clock=clock)
    bad = {"steps": 0, "queue_depth": 99,
           "speculative": {"rounds": 100, "num_draft": 4,
                           "acceptance_rate": 0.05}}
    fired = []
    for i in range(4):
        fired += det.observe(dict(bad, steps=i))
        clock.advance(1.0)
    kinds = sorted(a.kind for a in fired)
    assert kinds == ["accept_collapse", "queue_saturation"]


def test_accept_collapse_needs_volume():
    det = AnomalyDetector(Thresholds(sustain=1, accept_floor=0.2,
                                     accept_min_drafted=400),
                          clock=FakeClock())
    assert det.observe({"speculative": {
        "rounds": 10, "num_draft": 4, "acceptance_rate": 0.0}}) == []


@pytest.mark.parametrize("scenario", ["straggler", "stall", "slo"])
def test_each_scenario_produces_exactly_one_bundle(tmp_path, scenario):
    """The acceptance bar: a deterministic fake-clock scenario drives
    the monitor end to end and EXACTLY ONE postmortem bundle lands on
    disk."""
    clock = FakeClock()
    postmortem.set_postmortem_writer(
        postmortem.PostmortemWriter(str(tmp_path), clock=clock))
    mon = AnomalyMonitor(AnomalyDetector(TH, clock=clock),
                         min_interval_s=0.0, clock=clock,
                         config={"scenario": scenario})
    for i in range(20):
        if scenario == "straggler":
            stats = {"stages": _stages(20.0)}
        elif scenario == "slo":
            stats = {"steps": i, "latency": {"ttft_p95_ms": 250.0}}
        else:                                    # stall
            stats = {"steps": 7, "active_slots": 2, "queue_depth": 1}
        mon.observe(stats)
        clock.advance(5.0)
    bundles = sorted(p for p in tmp_path.iterdir() if p.name.startswith(
        "pm-"))
    assert len(bundles) == 1, (scenario, bundles)
    assert len(mon.bundles) == 1
    import json
    manifest = json.loads((bundles[0] / "manifest.json").read_text())
    expected = {"straggler": "straggler_hop", "slo": "slo_ttft",
                "stall": "pipeline_stall"}[scenario]
    assert manifest["reason"] == expected
    assert (bundles[0] / "flight.jsonl").exists()
    assert (bundles[0] / "metrics.prom").exists()


def test_monitor_throttles_and_accepts_callable():
    clock = FakeClock()
    calls = []

    def stats():
        calls.append(1)
        return {"steps": len(calls)}

    mon = AnomalyMonitor(AnomalyDetector(TH, clock=clock),
                         min_interval_s=1.0, clock=clock)
    mon.observe(stats)
    mon.observe(stats)                           # throttled: not built
    assert len(calls) == 1
    clock.advance(2.0)
    mon.observe(stats)
    assert len(calls) == 2


def test_header_backend_stats_poll_drives_straggler_detection(tmp_path):
    """Production wiring for observe_stages: every HeaderBackend stats
    collection (the /stats and /metrics poll path) feeds the straggler
    detector, so a scheduled Prometheus scrape fires the anomaly and
    writes the bundle."""
    from distributed_inference_demo_tpu.runtime.http_server import (
        HeaderBackend)

    postmortem.set_postmortem_writer(
        postmortem.PostmortemWriter(str(tmp_path)))

    class StubHeader:
        def collect_stats(self, num_stages, timeout=10.0):
            return [
                {"role": "header", "device_id": "h",
                 "compute_p95_ms": 2.0},
                {"role": "worker", "device_id": "w1",
                 "compute_p95_ms": 2.0},
                {"role": "tail", "device_id": "w2",
                 "compute_p95_ms": 40.0},
            ]

    backend = HeaderBackend(StubHeader(), max_seq=64, num_stages=3)
    clock = FakeClock()
    backend.anomaly = __import__(
        "distributed_inference_demo_tpu.telemetry.anomaly",
        fromlist=["AnomalyMonitor"]).AnomalyMonitor(
        AnomalyDetector(TH, clock=clock), min_interval_s=0.0,
        clock=clock, config={"backend": "HeaderBackend"})
    for _ in range(5):
        backend.stats()
        clock.advance(5.0)
    bundles = list(tmp_path.glob("pm-*"))
    assert len(bundles) == 1
    import json
    manifest = json.loads((bundles[0] / "manifest.json").read_text())
    assert manifest["reason"] == "straggler_hop"
    assert manifest["detail"]["detail"]["device"] == "w2"
    assert backend.debug_state()["anomaly"]["recent"]


def test_anomaly_to_dict_round_trips():
    a = Anomaly("straggler_hop", "warn", 12.5, {"device": "w2"})
    assert a.to_dict() == {"kind": "straggler_hop", "severity": "warn",
                           "ts": 12.5, "detail": {"device": "w2"}}
