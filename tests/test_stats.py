"""Hot-loop observability: per-stage timers, byte counts, stats polling,
and the /stats HTTP endpoint (VERDICT r1 item 9; reference
``Communication.java:104-107,650-661,859-896``)."""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.base import (
    slice_stage, split_layer_ranges)
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime.distributed import (
    PipelineHeader, PipelineWorker, StageRuntime)
from distributed_inference_demo_tpu.runtime.http_server import (
    HeaderBackend, InferenceHTTPServer)
from distributed_inference_demo_tpu.runtime.stats import StageStats, _percentile

GREEDY = SamplingParams(greedy=True)
PROMPT = np.array([[5, 17, 42, 7, 99, 3, 12, 56]], dtype=np.int32)


def _build(num_stages=2, max_seq=64):
    cfg = get_model_config("llama-test")
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, num_stages)
    net = LoopbackNetwork()
    ids = [f"s{i}" for i in range(num_stages)]
    transports = [LoopbackTransport(d, net) for d in ids]
    header = PipelineHeader(
        StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                     max_seq, GREEDY),
        transports[0], next_id=ids[1], step_timeout=60)
    workers = []
    for i in range(1, num_stages):
        workers.append(PipelineWorker(
            StageRuntime(cfg, specs[i], slice_stage(full, cfg, specs[i]),
                         max_seq, GREEDY),
            transports[i],
            next_id=ids[i + 1] if i + 1 < num_stages else None,
            header_id=ids[0], step_timeout=60))
    threads = [threading.Thread(target=w.serve_forever, daemon=True)
               for w in workers]
    for t in threads:
        t.start()
    return header, workers, threads


@pytest.mark.quick
def test_percentile_helper():
    assert _percentile([], 50) != _percentile([], 50)  # nan
    xs = list(range(1, 101))
    assert _percentile(xs, 50) == 50
    assert _percentile(xs, 95) == 95
    assert _percentile(xs, 99) == 99
    assert _percentile([7.0], 95) == 7.0
    assert _percentile([7.0], 99) == 7.0


# tier-1 budget: http_stats_endpoint + stats_reset are the quick-lane
# reps for the recording plumbing; the full pipeline run rides slow
@pytest.mark.slow
def test_pipeline_records_stats():
    header, workers, threads = _build(num_stages=3)
    new = 6
    header.generate(PROMPT, new)

    h = header.stats.snapshot()
    # header computes prefill + (new-1) decode chunks (last token ends req)
    assert h["role"] == "header"
    assert h["steps"] == new  # 1 prefill + new-1 decode chunks... see below
    assert h["messages_out"] >= new          # h chunks (+ end is untimed)
    assert h["messages_in"] == new           # one tok per step
    assert h["bytes_out"] > 0 and h["bytes_in"] > 0
    assert h["compute_s"] > 0 and h["recv_wait_s"] > 0
    assert "ring_rtt_p50_ms" in h and h["ring_rtt_p50_ms"] >= 0
    assert "ring_rtt_p95_ms" in h
    assert h["ring_rtt_p95_ms"] >= h["ring_rtt_p50_ms"]
    assert h["ring_rtt_p99_ms"] >= h["ring_rtt_p95_ms"]

    stats = header.collect_stats(num_stages=3)
    header.shutdown_pipeline()
    for t in threads:
        t.join(timeout=30)
    assert len(stats) == 3
    assert stats[0]["role"] == "header"
    roles = {s["role"] for s in stats[1:]}
    assert roles == {"worker", "tail"}
    for s in stats[1:]:
        assert s["steps"] == new             # prefill + new-1 decode... per stage
        assert s["bytes_in"] > 0 and s["bytes_out"] > 0
        assert s["compute_s"] > 0
        assert "compute_p50_ms" in s
        assert s["device_id"] in ("s1", "s2")


def test_stats_reset():
    s = StageStats("x")
    s.record_compute(0.5)
    s.record_recv(0.1, 100)
    s.record_send(0.1, 50)
    s.record_rtt(0.2)
    assert s.snapshot()["steps"] == 1
    s.reset()
    snap = s.snapshot()
    assert snap["steps"] == 0 and snap["bytes_in"] == 0
    assert "ring_rtt_p50_ms" not in snap


def test_http_stats_endpoint():
    header, workers, threads = _build(num_stages=2)
    backend = HeaderBackend(header, max_seq=64, num_stages=2)
    srv = InferenceHTTPServer(backend, model_name="llama-test")
    srv.start()
    try:
        url = f"http://{srv.host}:{srv.port}"
        body = json.dumps({"prompt_ids": PROMPT.tolist(),
                           "max_new_tokens": 4}).encode()
        req = urllib.request.Request(url + "/generate", data=body,
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            assert json.loads(r.read())["tokens"]

        with urllib.request.urlopen(url + "/stats", timeout=60) as r:
            stats = json.loads(r.read())
        assert len(stats["stages"]) == 2
        assert stats["stages"][0]["role"] == "header"
        assert stats["stages"][1]["role"] == "tail"
        assert stats["stages"][1]["steps"] == 4
    finally:
        srv.shutdown()
        header.shutdown_pipeline()
        for t in threads:
            t.join(timeout=30)
