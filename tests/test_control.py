"""Control plane tests: message schema, device pool, registration service,
lifecycle FSM.

The reference has zero tests for any of this (SURVEY.md §4); these exercise
the typed re-implementations of server.py:38-473 and Client.java:50-173 over
real localhost sockets with ephemeral ports.
"""

import threading
import time

import pytest

from distributed_inference_demo_tpu.control.messages import (
    Envelope, MsgType, PROTOCOL_VERSION, decode, encode, make)
from distributed_inference_demo_tpu.control.pool import (
    DeviceInfo, DevicePoolManager, DeviceRole)
from distributed_inference_demo_tpu.control.service import (
    RegistrationClient, RegistrationService)
from distributed_inference_demo_tpu.control.lifecycle import (
    LifecycleClient, LifecycleServer, LifecycleState, RunConfig)


# ---------------------------------------------------------------- messages

def test_message_roundtrip():
    msg = Envelope(MsgType.REGISTER, {"device_id": "d0", "address": "a:1",
                                      "capabilities": {"mem": 8}})
    out = decode(encode(msg))
    assert out.type == MsgType.REGISTER
    assert out.get("device_id") == "d0"
    assert out.get("capabilities") == {"mem": 8}


def test_message_rejects_wrong_version():
    import msgpack
    raw = msgpack.packb({"v": PROTOCOL_VERSION + 1, "t": "register"})
    with pytest.raises(ValueError, match="version"):
        decode(raw)


def test_message_rejects_untagged():
    import msgpack
    with pytest.raises(ValueError):
        decode(msgpack.packb({"foo": 1}))


def test_binary_payload_survives():
    blob = bytes(range(256))
    out = decode(make(MsgType.ARTIFACT_CHUNK, data=blob))
    assert out.get("data") == blob


# -------------------------------------------------------------------- pool

def make_pool(timeout=30.0):
    clock = {"t": 1000.0}
    pool = DevicePoolManager(heartbeat_timeout=timeout,
                             clock=lambda: clock["t"])
    return pool, clock


def dev(i, role=DeviceRole.WORKER, addr=None):
    return DeviceInfo(device_id=f"d{i}", address=addr or f"10.0.0.{i}:1234",
                      role=role)


def test_pool_register_and_duplicate_address():
    pool, _ = make_pool()
    assert pool.register_device(dev(0))
    assert pool.register_device(dev(1))
    # same address, different id -> rejected (server.py:131-153)
    assert not pool.register_device(dev(2, addr="10.0.0.1:1234"))
    # same id re-registering -> refresh, ok
    assert pool.register_device(dev(0))
    assert len(pool.devices) == 2


def test_pool_allocation_header_first_tail_last():
    pool, _ = make_pool()
    pool.register_device(dev(0, DeviceRole.WORKER))
    pool.register_device(dev(1, DeviceRole.TAIL))
    pool.register_device(dev(2, DeviceRole.HEADER))
    pool.register_device(dev(3, DeviceRole.WORKER))
    chosen = pool.allocate_devices_for_task("t1", 4)
    assert chosen is not None
    assert chosen[0].role == DeviceRole.HEADER      # server.py:261-267
    assert chosen[-1].role == DeviceRole.TAIL
    assert all(d.status == "allocated" and d.task_id == "t1" for d in chosen)
    # pool exhausted
    assert pool.allocate_devices_for_task("t2", 1) is None
    # release returns them
    assert pool.release_task_devices("t1") == 4
    assert len(pool.get_available_devices()) == 4


def test_pool_heartbeat_timeout_moves_to_failed():
    pool, clock = make_pool(timeout=30.0)
    pool.register_device(dev(0))
    pool.register_device(dev(1))
    failures = []
    pool.on_failure(failures.append)

    clock["t"] += 20
    pool.heartbeat("d1")             # d1 stays fresh
    clock["t"] += 15                 # d0 now 35s stale, d1 15s
    failed = pool.check_device_heartbeats()
    assert [d.device_id for d in failed] == ["d0"]
    assert failures[0].device_id == "d0"
    assert "timeout" in failures[0].failure_reason
    assert failures[0].failure_time == clock["t"]
    assert "d0" not in pool.devices
    assert pool.get_failed_devices()[0].device_id == "d0"
    # re-registration rejoins cleanly (reconnect path, client.py:51-82)
    assert pool.register_device(dev(0))
    assert not pool.get_failed_devices()


def test_pool_status_snapshot():
    pool, clock = make_pool(timeout=5.0)
    pool.register_device(dev(0, DeviceRole.HEADER))
    pool.register_device(dev(1))
    clock["t"] += 10
    pool.check_device_heartbeats()
    snap = pool.status_snapshot()
    assert snap["total"] == 0 and len(snap["failed"]) == 2


# ------------------------------------------------- registration service

@pytest.fixture
def reg_service():
    pool = DevicePoolManager(heartbeat_timeout=30.0)
    svc = RegistrationService(pool)
    svc.start()
    yield svc, pool
    svc.stop()


def test_registration_over_sockets(reg_service):
    svc, pool = reg_service
    cli = RegistrationClient(svc.address, "dev-a", "127.0.0.1:9000",
                             role=DeviceRole.HEADER, model="tinyllama-1.1b",
                             capabilities={"platform": "tpu", "mem_gb": 16})
    try:
        assert cli.register()
        assert cli.heartbeat_once()
        status = cli.get_status()
        entry = status["devices"]["dev-a"]
        assert entry["role"] == "header"
        assert entry["model"] == "tinyllama-1.1b"
        assert pool.devices["dev-a"].capabilities["platform"] == "tpu"
    finally:
        cli.close()


def test_registration_duplicate_rejected(reg_service):
    svc, _ = reg_service
    a = RegistrationClient(svc.address, "dev-a", "127.0.0.1:9000")
    b = RegistrationClient(svc.address, "dev-b", "127.0.0.1:9000")
    try:
        assert a.register()
        assert not b.register()      # same data-plane address
    finally:
        a.close()
        b.close()


# ------------------------------------------------------------- lifecycle

def run_config(n=2):
    ids = [f"d{i}" for i in range(n)]
    return RunConfig(
        model="llama-test", num_samples=2, max_new_tokens=8, pool_size=1,
        device_graph=[f"127.0.0.1:{9100+i}" for i in range(n)],
        device_ids=ids,
        stage_ranges={ids[0]: [0, 2], ids[-1]: [2, 4]},
        mesh_axes={"dp": 1, "tp": 1},
        kv_cache_dtype="float8_e4m3fn")


def test_runconfig_roundtrip():
    cfg = run_config()
    out = RunConfig.from_payload(
        decode(make(MsgType.OPEN, config=cfg.to_payload())).get("config"))
    assert out == cfg


def test_lifecycle_full_handshake():
    cfg = run_config(2)
    artifacts = {"weights-d0": b"\x01" * (3 << 20),  # >1 chunk
                 "weights-d1": b"\x02" * 10}
    server = LifecycleServer(
        cfg, artifact_provider=lambda dev, name: artifacts[name])
    server.start()
    results = {}

    def device(dev_id):
        cli = LifecycleClient(server.address, dev_id)
        try:
            got = cli.open()
            assert got.model == "llama-test"
            blob = cli.fetch_artifact(f"weights-{dev_id}")
            cli.initialized(wait_start=True)
            assert cli.state == LifecycleState.RUNNING
            cli.finish()
            assert cli.state == LifecycleState.CLOSED
            results[dev_id] = blob
        finally:
            cli.close()

    threads = [threading.Thread(target=device, args=(d,))
               for d in cfg.device_ids]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=15)
        assert not t.is_alive()
    assert server.wait_all_finished(timeout=5)
    assert results["d0"] == artifacts["weights-d0"]
    assert results["d1"] == artifacts["weights-d1"]
    server.stop()


def test_lifecycle_start_barrier_waits_for_all():
    """No device gets START until every device is INITIALIZED."""
    cfg = run_config(2)
    server = LifecycleServer(cfg)
    server.start()
    try:
        c0 = LifecycleClient(server.address, "d0", timeout_ms=2000)
        c1 = LifecycleClient(server.address, "d1", timeout_ms=2000)
        c0.open()
        c1.open()
        c0._sock.send(make(MsgType.INITIALIZED, device_id="d0"))
        time.sleep(0.3)
        assert not server.all_running.is_set()
        c1.initialized(wait_start=True)
        # now d0's START should be waiting in its queue
        c0.initialized = None  # (already sent); just receive START
        msg = decode(c0._sock.recv())
        assert msg.type == MsgType.START
        assert server.all_running.is_set()
        c0.close()
        c1.close()
    finally:
        server.stop()


def test_lifecycle_artifact_ok_unknown_and_rejoin():
    cfg = run_config(1)
    cfg.device_ids = ["d0"]

    def provider(dev, name):
        if name != "weights":
            raise KeyError(name)
        return b"payload"

    server = LifecycleServer(cfg, artifact_provider=provider)
    server.start()
    try:
        cli = LifecycleClient(server.address, "d0", timeout_ms=2000)
        cli.open()
        assert cli.fetch_artifact("weights") == b"payload"
        # unknown artifact -> typed error surfaced as RuntimeError
        with pytest.raises(RuntimeError, match="unknown artifact"):
            cli.fetch_artifact("nonexistent")
        cli.initialized(wait_start=True)
        # a device re-initializing after the run started (rejoin) gets its
        # own START; no duplicate broadcast poisons other devices' queues
        cli._sock.send(make(MsgType.INITIALIZED, device_id="d0"))
        msg = decode(cli._sock.recv())
        assert msg.type == MsgType.START
        cli.finish()   # next recv must be CLOSE, not a stale START
        cli.close()
    finally:
        server.stop()
