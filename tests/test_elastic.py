"""Elasticity: live migration, scale-up/down, failure-triggered re-planning
with token-preserving resume, and the control-plane heartbeat wiring.

The property under test everywhere: whatever happens to the pipeline
topology mid-run, greedy output must equal the single-engine reference
token for token (the reference can only hang on failure — SURVEY.md §5.3).
"""

import threading
import time

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.comm.transport import (
    LoopbackNetwork, LoopbackTransport, TransportTimeout)
from distributed_inference_demo_tpu.control.pool import (
    DeviceInfo, DevicePoolManager, DeviceRole)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.elastic import (
    ElasticHeader, ElasticStageRuntime, ElasticWorker)

GREEDY = SamplingParams(greedy=True)
PROMPT = np.array([[5, 17, 42, 7, 99, 3, 12, 56]], dtype=np.int32)
MODEL = "llama-test"


def reference_tokens(prompt, max_new):
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    return InferenceEngine(cfg, params, max_seq=64,
                           sampling=GREEDY).generate(prompt, max_new).tokens


class DyingWorker(ElasticWorker):
    """Simulates a crash: stops serving after N data chunks (no goodbye)."""

    def __init__(self, *args, die_after: int, **kw):
        super().__init__(*args, **kw)
        self.die_after = die_after
        self._seen = 0

    def serve_forever(self, idle_timeout=None):
        while True:
            try:
                tag, payload = self.transport.recv_any(
                    timeout=idle_timeout or self.step_timeout)
            except TransportTimeout:
                return          # clean idle exit (mirrors the base class)
            if tag.startswith("h:"):
                self._seen += 1
                if self._seen > self.die_after:
                    return      # crash: message dropped on the floor
            if not self.handle_message(tag, payload):
                return


def build_elastic(num_stages, dying=None, spares=0, max_seq=64):
    """Elastic pipeline on loopback; returns (header, workers, threads).

    ``dying``: {device_id: die_after} — those workers crash after N chunks.
    """
    cfg = get_model_config(MODEL)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    from distributed_inference_demo_tpu.models.base import split_layer_ranges
    specs = split_layer_ranges(cfg.num_layers, num_stages)
    net = LoopbackNetwork()
    n_all = num_stages + spares
    ids = [f"s{i}" for i in range(n_all)]
    transports = [LoopbackTransport(d, net) for d in ids]

    header = ElasticHeader(
        ElasticStageRuntime(cfg, specs[0], full, max_seq, GREEDY),
        transports[0], chain=ids[:num_stages], step_timeout=60,
        poll_interval=0.2)
    workers = []
    dying = dying or {}
    for i in range(1, n_all):
        # spares start parked on the last stage's range; a reshard
        # reassigns them before they ever see traffic.
        spec = specs[min(i, num_stages - 1)]
        rt = ElasticStageRuntime(cfg, spec, full, max_seq, GREEDY)
        if ids[i] in dying:
            workers.append(DyingWorker(
                rt, transports[i],
                next_id=ids[i + 1] if i + 1 < num_stages else None,
                header_id=ids[0], step_timeout=60,
                die_after=dying[ids[i]]))
        else:
            workers.append(ElasticWorker(
                rt, transports[i],
                next_id=ids[i + 1] if i + 1 < num_stages else None,
                header_id=ids[0], step_timeout=60))
    threads = [threading.Thread(target=w.serve_forever, args=(30,),
                                daemon=True) for w in workers]
    for t in threads:
        t.start()
    return header, workers, threads


def _stop_all(header, extra_ids=()):
    header.shutdown_pipeline()
    for dev in extra_ids:
        header.transport.send(dev, "stop", b"")


@pytest.mark.slow
def test_live_migration_scale_down_park_and_rejoin():
    """Planned migration: 3 stages -> 2 (the dropped live worker is parked:
    caches freed, standing by) -> back to 3 (the spare rejoins).  Every
    configuration must match the reference (the ModifySession capability,
    with a working trigger)."""
    want = reference_tokens(PROMPT, 10)
    header, workers, threads = build_elastic(3)
    got3 = header.generate(PROMPT, 10)
    np.testing.assert_array_equal(got3, want)

    header.reshard(["s0", "s1"])          # drop s2, re-split layers
    assert workers[1].rt.caches == {}     # s2 parked: caches freed
    got2 = header.generate(PROMPT, 10)
    np.testing.assert_array_equal(got2, want)
    assert workers[1].rt.caches == {}     # parked spare saw no traffic

    header.reshard(["s0", "s1", "s2"])    # the parked spare rejoins
    np.testing.assert_array_equal(header.generate(PROMPT, 10), want)
    _stop_all(header)
    for t in threads:
        t.join(timeout=30)


# tier-1 budget: heartbeat-reshard plus the test_migration live tests
# are the quick-lane reps; the scale-up soak rides the slow lane
@pytest.mark.slow
def test_live_migration_scale_up():
    """Scale-up: a spare worker joins the chain via reshard."""
    want = reference_tokens(PROMPT, 10)
    header, workers, threads = build_elastic(2, spares=1)
    np.testing.assert_array_equal(header.generate(PROMPT, 10), want)

    header.reshard(["s0", "s1", "s2"])    # spare s2 becomes the tail
    np.testing.assert_array_equal(header.generate(PROMPT, 10), want)
    assert workers[-1].rt.spec.is_last    # s2 really owns the tail now
    _stop_all(header)
    for t in threads:
        t.join(timeout=30)


@pytest.mark.slow
def test_failure_mid_generation_resumes():
    """A mid-chain worker dies after 4 chunks; a failure signal triggers
    re-planning and the request resumes, producing the exact reference
    tokens (the hang the reference exhibits is the bug, SURVEY.md §5.3)."""
    want = reference_tokens(PROMPT, 12)
    header, workers, threads = build_elastic(3, dying={"s1": 4})

    # watchdog stands in for the heartbeat sweeper (tested separately below)
    killer = threading.Timer(2.0, lambda: header.signal_failure("s1"))
    killer.start()
    got = header.generate(PROMPT, 12)
    np.testing.assert_array_equal(got, want)
    assert header.chain == ["s0", "s2"]
    _stop_all(header)
    killer.cancel()


def test_heartbeat_failure_triggers_reshard():
    """Control-plane wiring: DevicePoolManager's sweeper detects the dead
    device (no heartbeats) and its on_failure callback drives the header's
    reshard — no manual signal anywhere."""
    want = reference_tokens(PROMPT, 12)
    header, workers, threads = build_elastic(3, dying={"s1": 4})

    pool = DevicePoolManager(heartbeat_timeout=1.2)
    for dev in ["s0", "s1", "s2"]:
        pool.register_device(DeviceInfo(device_id=dev, address=dev,
                                        role=DeviceRole.WORKER))
    pool.on_failure(lambda info: header.signal_failure(info.device_id))

    alive = {"s0", "s2"}
    stop_beats = threading.Event()

    def beat():
        while not stop_beats.is_set():
            for dev in alive:
                pool.heartbeat(dev)
            time.sleep(0.2)

    beater = threading.Thread(target=beat, daemon=True)
    beater.start()
    pool.start_sweeper(interval=0.3)
    try:
        got = header.generate(PROMPT, 12)
    finally:
        pool.stop_sweeper()
        stop_beats.set()
    np.testing.assert_array_equal(got, want)
    assert header.chain == ["s0", "s2"]
    assert [d.device_id for d in pool.get_failed_devices()] == ["s1"]
    _stop_all(header)


def test_reshard_below_two_devices_raises():
    header, workers, threads = build_elastic(2)
    with pytest.raises(RuntimeError, match="enough devices"):
        header.reshard(["s0"])
    _stop_all(header)


def test_stale_epoch_ack_does_not_satisfy_reshard():
    """ADVICE r1 #3: a delayed ack from reshard N must not satisfy reshard
    N+1's ack-wait.  No worker threads here — acks are injected by hand."""
    from distributed_inference_demo_tpu.models.base import split_layer_ranges

    cfg = get_model_config(MODEL)
    full = init_full_params(jax.random.PRNGKey(0), cfg)
    specs = split_layer_ranges(cfg.num_layers, 2)
    net = LoopbackNetwork()
    t0 = LoopbackTransport("s0", net)
    t1 = LoopbackTransport("s1", net)
    header = ElasticHeader(
        ElasticStageRuntime(cfg, specs[0], full, 64, GREEDY),
        t0, chain=["s0", "s1"], step_timeout=1.0, poll_interval=0.1)

    # stale ack (epoch 0) already queued when reshard (-> epoch 1) starts:
    # it must be ignored, so the ack-wait times out.
    t1.send("s0", "rack:s1:0", b"")
    with pytest.raises(TransportTimeout, match="reshard acks"):
        header.reshard(["s0", "s1"])

    # a current-epoch ack (next reshard -> epoch 2) satisfies the wait.
    t1.send("s0", "rack:s1:2", b"")
    header.reshard(["s0", "s1"])
    assert header.epoch == 2
