"""Continuous batching: greedy parity with the plain engine, late joiners,
slot reuse under oversubscription, streaming.

Greedy decoding is the oracle: whatever mix of requests shares the slot
pool, each request's tokens must be bit-identical to running it alone
through InferenceEngine — continuous batching is a scheduling feature,
never a semantics change.
"""

import threading
import time

import jax
import numpy as np
import pytest

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def oracle(params):
    return InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY)


def expected(oracle, prompt, n):
    return oracle.generate(np.asarray(prompt)[None, :], n).tokens[0]


@pytest.mark.quick
def test_single_request_matches_engine(params, oracle):
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY,
                                  prompt_buckets=(16, 64)) as eng:
        prompt = [3, 14, 15, 92, 65]
        got = eng.submit(prompt, 12).wait(timeout=300)
        np.testing.assert_array_equal(got, expected(oracle, prompt, 12))


@pytest.mark.slow
def test_concurrent_requests_all_match(params, oracle):
    # slow lane: test_paged_batching's cold-parity concurrent test is
    # the quick rep for concurrent-request parity on the (paged-native)
    # scheduler; this is the ragged-lengths twin of the same claim
    prompts = [[3, 14, 15], [9, 2, 6, 5, 3, 5], [1], [7, 7, 7, 7]]
    ns = [10, 14, 8, 12]
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        reqs = [eng.submit(p, n) for p, n in zip(prompts, ns)]
        for p, n, r in zip(prompts, ns, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, n))


# tier-1 budget: test_decode_block_parity_and_late_joiner is the
# quick-lane late-joiner rep (same seam through the fused loop)
@pytest.mark.slow
def test_late_joiner_matches(params, oracle):
    """A request admitted while another is mid-decode must still be
    bit-exact — the continuous part of continuous batching."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        first = eng.submit([5, 4, 3, 2], 40)
        deadline = time.monotonic() + 240
        while len(first.tokens) < 5:        # provably mid-flight
            assert time.monotonic() < deadline, "first request stalled"
            time.sleep(0.01)
        assert not first.done.is_set()
        second = eng.submit([8, 8, 1], 10)
        np.testing.assert_array_equal(second.wait(timeout=300),
                                      expected(oracle, [8, 8, 1], 10))
        np.testing.assert_array_equal(first.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 40))


def test_oversubscribed_slots(params, oracle):
    """More requests than slots: later requests queue for a freed slot
    and still come out exact."""
    prompts = [[i + 1, i + 2, i + 3] for i in range(5)]
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        reqs = [eng.submit(p, 9) for p in prompts]
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, 9))


def test_generate_surface_and_threads(params, oracle):
    """The engine-surface generate() batches rows submitted from separate
    threads (the HTTP handler's usage pattern)."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        results = {}

        def run(name, prompt, n):
            results[name] = eng.generate(np.asarray([prompt]), n).tokens[0]

        ts = [threading.Thread(target=run, args=(i, p, 11))
              for i, p in enumerate([[4, 5], [6, 7, 8], [9]])]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        for i, p in enumerate([[4, 5], [6, 7, 8], [9]]):
            np.testing.assert_array_equal(results[i],
                                          expected(oracle, p, 11))


def test_stream_yields_incrementally(params):
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        steps = list(eng.generate_stream(np.asarray([[1, 2, 3]]), 7))
        assert len(steps) == 7
        assert all(s.shape == (1,) for s in steps)
        # and the streamed tokens equal the blocking path's
        blocking = eng.generate(np.asarray([[1, 2, 3]]), 7).tokens[0]
        np.testing.assert_array_equal(np.concatenate(steps), blocking)


def test_stream_with_early_eos_row_terminates(params, oracle):
    """Multi-row stream where one row hits EOS early must not deadlock:
    the finished row pads with eos while the other row keeps streaming
    (regression: the consumer used to re-block on the exhausted queue)."""
    # pick the first greedy token of row A as the EOS id: row A finishes
    # after 1 token, row B (different first token) runs the full length
    row_a, row_b = [5, 4, 3, 2], [8, 8, 1, 7]
    eos = int(expected(oracle, row_a, 1)[0])
    assert int(expected(oracle, row_b, 1)[0]) != eos
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, eos_id=eos,
                                  prompt_buckets=(16,)) as eng:
        steps = list(eng.generate_stream(np.asarray([row_a, row_b]), 6))
        assert len(steps) == 6
        assert steps[0][0] == eos                 # row A's only token
        assert all(s[0] == eos for s in steps[1:])  # then padded
        got_b = np.asarray([s[1] for s in steps])
        np.testing.assert_array_equal(got_b, expected(oracle, row_b, 6))


def test_cancel_frees_slot(params, oracle):
    """Cancelling a queued/in-flight request frees its slot for the next
    one; produced-so-far tokens remain readable."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=1,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        long = eng.submit([5, 4, 3, 2], 500 // 8)
        queued = eng.submit([1, 2], 30)       # waits: only one slot
        queued.cancel()
        deadline = time.monotonic() + 240
        while not queued.done.is_set():
            assert time.monotonic() < deadline, "cancel not honored"
            time.sleep(0.01)
        follow = eng.submit([8, 8, 1], 10)    # gets the slot after `long`
        np.testing.assert_array_equal(follow.wait(timeout=300),
                                      expected(oracle, [8, 8, 1], 10))
        long.cancel()


def test_kvcache_exact_repeat(params, oracle):
    """A repeated prompt reuses every whole block below plen-1 and still
    decodes greedy-exact (the old full-prompt-LRU exact-repeat case,
    ported to the block cache)."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  kv_cache_blocks=16,
                                  kv_block_tokens=2) as eng:
        prompt = [3, 14, 15, 92, 65, 35, 89]
        want = expected(oracle, prompt, 10)
        first = eng.submit(prompt, 10).wait(timeout=300)
        second = eng.submit(prompt, 10).wait(timeout=300)
        np.testing.assert_array_equal(first, want)
        np.testing.assert_array_equal(second, want)
        st = eng.kv_cache.stats
        assert st["hits"] == 1
        # 7 tokens, 2-token blocks, reuse capped below plen: 3 blocks
        assert st["partial_hit_tokens"] == 6


def test_kvcache_shared_prefix_divergent_tail(params, oracle):
    """Two prompts sharing a long prefix: the second reuses the shared
    whole blocks only and its full output stays greedy-exact."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  kv_cache_blocks=16,
                                  kv_block_tokens=2) as eng:
        shared = [7, 3, 9, 1, 4, 6]
        a, b = shared + [11, 12], shared + [20, 21, 22]
        got_a = eng.submit(a, 8).wait(timeout=300)
        got_b = eng.submit(b, 8).wait(timeout=300)
        np.testing.assert_array_equal(got_a, expected(oracle, a, 8))
        np.testing.assert_array_equal(got_b, expected(oracle, b, 8))
        st = eng.kv_cache.stats
        assert st["hits"] == 1
        assert st["partial_hit_tokens"] == len(shared)


# slow lane: partial-hit twin; exact_repeat, shared_prefix_divergent_tail
# and below_block_and_pool_bound keep the prefix-cache seam quick
@pytest.mark.slow
def test_kvcache_mid_prompt_partial_hit_observable(params, oracle):
    """ISSUE 3 generality: a MID-prompt partial hit — shared prefix
    strictly shorter than the cached prompt AND the new prompt — reuses
    >= block_tokens tokens, lands on dwt_kvcache_partial_hit_tokens_total,
    and records a flight-recorder kvcache_hit event."""
    from distributed_inference_demo_tpu.telemetry import catalog
    from distributed_inference_demo_tpu.telemetry.flightrecorder import (
        get_flight_recorder)

    block_tokens = 4
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  kv_cache_blocks=16,
                                  kv_block_tokens=block_tokens) as eng:
        cached = list(range(2, 14))            # 12 tokens -> 3 blocks
        new = cached[:9] + [51, 52]            # diverges inside block 3
        np.testing.assert_array_equal(
            eng.submit(cached, 6).wait(timeout=300),
            expected(oracle, cached, 6))
        np.testing.assert_array_equal(
            eng.submit(new, 6).wait(timeout=300),
            expected(oracle, new, 6))
        st = eng.kv_cache.stats
        assert st["hits"] == 1
        reused = st["partial_hit_tokens"]
        assert reused >= block_tokens
        assert reused == 8                     # 2 whole blocks of the 9
        assert reused < len(cached) and reused < len(new)  # mid-prompt
        # the catalog bridge exposes the counter on /metrics
        text = catalog.scrape(eng)
        assert f"dwt_kvcache_partial_hit_tokens_total {reused}" in text
        # and the flight ring holds the hit event
        hits = [e for e in get_flight_recorder().snapshot()
                if e.get("kind") == "kvcache_hit"]
        assert hits and hits[-1]["tokens"] == reused


# slow lane: primed-vs-cold twin of test_kvcache_exact_repeat +
# test_kvcache_shared_prefix_divergent_tail, which stay quick
@pytest.mark.slow
def test_kvcache_primed_vs_cold_scheduler_exactness(params, oracle):
    """ISSUE 3 exactness (scheduler path): the same suffix-after-shared-
    prefix prompt decodes token-identically on a COLD engine and on an
    engine PRIMED with the shared prefix."""
    shared = list(range(3, 19))                  # 16 tokens = 2 blocks
    prompt = shared + [42, 43, 44]
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(32,),
                                  kv_cache_blocks=16,
                                  kv_block_tokens=8) as cold_eng:
        cold = cold_eng.submit(prompt, 10).wait(timeout=300)
        assert cold_eng.kv_cache.stats["hits"] == 0
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(32,),
                                  kv_cache_blocks=16,
                                  kv_block_tokens=8) as primed_eng:
        primed_eng.submit(shared + [99], 4).wait(timeout=300)  # prime
        primed = primed_eng.submit(prompt, 10).wait(timeout=300)
        assert primed_eng.kv_cache.stats["hits"] == 1
    np.testing.assert_array_equal(cold, primed)
    np.testing.assert_array_equal(cold, expected(oracle, prompt, 10))


def test_kvcache_below_block_and_pool_bound(params, oracle):
    """Sub-block overlaps don't trigger reuse; the pool bound holds
    under pressure (LRU leaf eviction, never over capacity)."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  kv_cache_blocks=3,
                                  kv_block_tokens=4) as eng:
        p1 = [1, 2, 3, 4, 9, 9]
        p2 = [1, 2, 3, 8, 8, 8]     # lcp=3 < block_tokens=4
        eng.submit(p1, 6).wait(timeout=300)
        got = eng.submit(p2, 6).wait(timeout=300)
        np.testing.assert_array_equal(got, expected(oracle, p2, 6))
        assert eng.kv_cache.stats["hits"] == 0
        for extra in ([5] * 8, [6] * 8, [7] * 8):
            eng.submit(extra, 4).wait(timeout=300)
        snap = eng.kv_cache.snapshot()
        assert snap["blocks_used"] <= 3          # pool bound enforced
        assert snap["evicted_blocks"] > 0        # pressure was real
        assert snap["resident_bytes"] <= snap["capacity_bytes"]


def test_kvcache_zero_blocks_means_default_pool(params, oracle):
    """There is no cache-off mode on the paged-native scheduler (the
    pool IS the decode cache): kv_cache_blocks=0 resolves to the
    dense-equivalent default pool and requests still come out exact."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  kv_cache_blocks=0) as eng:
        prompt = [3, 1, 4, 1, 5]
        for _ in range(2):
            got = eng.submit(prompt, 6).wait(timeout=300)
            np.testing.assert_array_equal(got, expected(oracle, prompt, 6))
        assert (eng.kv_cache.num_blocks
                == eng.max_batch * eng._table_width)


def test_submit_validation(params):
    with ContinuousBatchingEngine(CFG, params, max_seq=32, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        with pytest.raises(ValueError, match="exceeds"):
            eng.submit(list(range(30)), 10)
        with pytest.raises(ValueError, match="empty"):
            eng.submit([], 4)


def test_http_server_over_batching_backend(params, oracle):
    """The HTTP handler's backend surface works unchanged over the
    batching engine: concurrent POST /generate requests from separate
    connections share the slot pool and each comes back greedy-exact."""
    import http.client
    import json

    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)

    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        server = InferenceHTTPServer(eng, port=0, model_name="llama-test")
        server.start()
        try:
            results = {}

            def post(name, prompt, n):
                conn = http.client.HTTPConnection(server.host, server.port,
                                                  timeout=300)
                body = json.dumps({"prompt_ids": [prompt],
                                   "max_new_tokens": n})
                conn.request("POST", "/generate", body,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                results[name] = (resp.status,
                                 json.loads(resp.read()))
                conn.close()

            ts = [threading.Thread(target=post, args=(i, p, 10))
                  for i, p in enumerate([[2, 3, 4], [9, 8, 7, 6]])]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            for i, p in enumerate([[2, 3, 4], [9, 8, 7, 6]]):
                status, out = results[i]
                assert status == 200
                np.testing.assert_array_equal(
                    np.asarray(out["tokens"][0]), expected(oracle, p, 10))
        finally:
            server.shutdown()


def test_tp_mesh_batching_parity(params, oracle):
    """Continuous batching over a tp=2 mesh: ragged slots + prefix cache
    + tensor parallelism compose, greedy-exact vs the plain engine."""
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)

    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    sharded = shard_engine_params(params, CFG, mesh)
    with ContinuousBatchingEngine(CFG, sharded, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  kv_cache_blocks=16, kv_block_tokens=2,
                                  mesh=mesh) as eng:
        prompts = [[3, 14, 15, 92], [3, 14, 15, 92, 65, 35]]  # shared prefix
        reqs = [eng.submit(p, 10) for p in prompts]
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, 10))
        assert eng.kv_cache.stats["hits"] >= 1   # block reuse under tp


@pytest.mark.slow
def test_int8_weights_through_batching():
    """Quantized params flow through the slot engine unchanged (dense()
    dequantizes at the matmul): greedy parity vs the int8 plain engine."""
    from distributed_inference_demo_tpu.models.decoder import (
        init_full_params as init)

    cfg8 = get_model_config("llama-test-int8")
    params8 = init(jax.random.PRNGKey(0), cfg8, quantize=True)
    oracle8 = InferenceEngine(cfg8, params8, max_seq=96, sampling=GREEDY)
    with ContinuousBatchingEngine(cfg8, params8, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        prompt = [3, 14, 15, 92]
        got = eng.submit(prompt, 10).wait(timeout=300)
        want = oracle8.generate(np.asarray([prompt]), 10).tokens[0]
        np.testing.assert_array_equal(got, want)


def test_scheduler_crash_fails_waiters(params):
    """A decode-step failure (device lost, OOM, ...) must surface to every
    waiter instead of stranding them on a dead scheduler thread."""
    eng = ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                   sampling=GREEDY, prompt_buckets=(16,))
    try:
        def boom(*a, **k):
            raise RuntimeError("injected device failure")
        eng._paged_step = boom
        req = eng.submit([1, 2, 3], 20)
        with pytest.raises(RuntimeError, match="injected device failure"):
            req.wait(timeout=120)
        with pytest.raises(RuntimeError, match="closed"):
            eng.submit([4, 5], 5)
    finally:
        eng.close()


def test_close_fails_inflight(params):
    eng = ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                   sampling=GREEDY, prompt_buckets=(16,))
    req = eng.submit([1, 2, 3], 500 // 8)
    eng.close()
    try:
        req.wait(timeout=30)
    except RuntimeError:
        pass  # closed mid-flight -> error surfaced
    # (a fast machine may finish the request before close(); both are fine)


@pytest.mark.slow
def test_fp8_kv_cache(params):
    """Reduced-precision cache storage through the slot engine: runs end
    to end with finite outputs, and the tp combination is rejected."""
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh

    fp8_oracle = InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY,
                                 kv_cache_dtype="float8_e4m3fn")
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  kv_cache_dtype="float8_e4m3fn") as eng:
        assert str(eng._pk.dtype) == "float8_e4m3fn"
        prompt = [3, 14, 15, 92]
        got = eng.submit(prompt, 10).wait(timeout=300)
        # same insert-rounding + f32-upcast contract as the plain engine
        # => greedy parity holds for fp8 exactly as it does for f32
        want = fp8_oracle.generate(np.asarray([prompt]), 10).tokens[0]
        np.testing.assert_array_equal(got, want)
    # fp8 composes with tp: per-shard insert cast + read upcast => the
    # tp=2 slot engine matches the single-device fp8 oracle bit-exactly
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    sharded = shard_engine_params(params, CFG, mesh)
    with ContinuousBatchingEngine(CFG, sharded, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  mesh=mesh,
                                  kv_cache_dtype="float8_e4m3fn") as eng:
        prompt = [3, 14, 15, 92]
        got = eng.submit(prompt, 10).wait(timeout=300)
        want = fp8_oracle.generate(np.asarray([prompt]), 10).tokens[0]
        np.testing.assert_array_equal(got, want)


def test_submit_rejects_nonpositive_max_new(params):
    """Admission unconditionally records the first sampled token, so a
    max_new_tokens <= 0 request must be rejected at submit()."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1, 2, 3], 0)
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit([1, 2, 3], -4)


def test_stream_surfaces_scheduler_error(params):
    """A device/scheduler failure mid-request must raise out of the
    streaming consumer, not end the stream as a clean truncation."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        def boom(*a, **k):
            raise RuntimeError("injected device failure")
        eng._paged_prefill = boom            # admission path fails in the loop
        with pytest.raises(RuntimeError, match="injected device failure"):
            for _ in eng.generate_stream(np.asarray([1, 2, 3]), 4):
                pass


# ---------------------------------------------------------------------------
# speculative decoding inside the slot loop (batching x draft/verify)

import dataclasses

DRAFT_CFG = dataclasses.replace(CFG, num_layers=2)


@pytest.fixture(scope="module")
def draft_params():
    # different seed AND depth: a genuinely different proposer
    return init_full_params(jax.random.PRNGKey(1), DRAFT_CFG)


def spec_engine(params, draft_params, **kw):
    return ContinuousBatchingEngine(
        CFG, params, max_seq=96, max_batch=4, sampling=GREEDY,
        prompt_buckets=(16,), draft_cfg=DRAFT_CFG,
        draft_params=draft_params, num_draft=4, **kw)


def test_spec_single_request_matches_engine(params, draft_params, oracle):
    """Greedy speculative batching must be bit-identical to the plain
    engine — speculation AND batching are both pure scheduling."""
    with spec_engine(params, draft_params) as eng:
        prompt = [3, 14, 15, 92, 65]
        got = eng.submit(prompt, 12).wait(timeout=300)
        np.testing.assert_array_equal(got, expected(oracle, prompt, 12))
        assert eng.stats()["speculative"]["rounds"] >= 1


# tier-1 budget: test_spec_single_request_matches_engine keeps the
# quick-lane draft rep; concurrency twins ride the slow lane with
# the §22 mixed-spec suite pinning concurrent spec rows in tier-1
@pytest.mark.slow
def test_spec_concurrent_requests_all_match(params, draft_params, oracle):
    prompts = [[3, 14, 15], [9, 2, 6, 5, 3, 5], [1], [7, 7, 7, 7]]
    ns = [10, 14, 8, 12]
    with spec_engine(params, draft_params) as eng:
        reqs = [eng.submit(p, n) for p, n in zip(prompts, ns)]
        for p, n, r in zip(prompts, ns, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, n))


@pytest.mark.slow
def test_spec_late_joiner_matches(params, draft_params, oracle):
    """Admission between speculative rounds must stay bit-exact for both
    the in-flight and the joining request."""
    with spec_engine(params, draft_params) as eng:
        first = eng.submit([5, 4, 3, 2], 40)
        deadline = time.monotonic() + 240
        while len(first.tokens) < 5:
            assert time.monotonic() < deadline, "first request stalled"
            time.sleep(0.01)
        assert not first.done.is_set()
        second = eng.submit([8, 8, 1], 10)
        np.testing.assert_array_equal(second.wait(timeout=300),
                                      expected(oracle, [8, 8, 1], 10))
        np.testing.assert_array_equal(first.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 40))


@pytest.mark.slow
def test_spec_self_draft_accepts_everything(params):
    """Draft == target: greedy acceptance must be 1.0 and rounds must
    emit num_draft+1 tokens each (per-row advance, no lockstep min)."""
    with ContinuousBatchingEngine(
            CFG, params, max_seq=96, max_batch=2, sampling=GREEDY,
            prompt_buckets=(16,), draft_cfg=CFG, draft_params=params,
            num_draft=4) as eng:
        got = eng.submit([3, 1, 4], 21).wait(timeout=300)
        assert got.shape == (21,)
        st = eng.stats()["speculative"]
        assert st["acceptance_rate"] == 1.0
        # 1 prefill token + 20 from ceil(20/5)=4 all-accept rounds
        assert st["rounds"] == 4


def test_spec_eos_terminates_row_mid_block(params, draft_params, oracle):
    """A row whose eos lands inside an accepted block must finish with
    exactly the oracle's eos-truncated output."""
    prompt = [3, 14, 15, 92, 65]
    ref = expected(oracle, prompt, 12)
    eos = int(ref[4])
    want = list(ref[:5])                       # truncated AT first eos
    with ContinuousBatchingEngine(
            CFG, params, max_seq=96, max_batch=4, sampling=GREEDY,
            prompt_buckets=(16,), eos_id=eos, draft_cfg=DRAFT_CFG,
            draft_params=draft_params, num_draft=4) as eng:
        got = eng.submit(prompt, 12).wait(timeout=300)
        np.testing.assert_array_equal(got, want)


@pytest.mark.slow
def test_spec_stream_matches_plain_stream(params, draft_params):
    """Streaming through the speculative slot loop yields the same
    per-step rows as the non-draft batching engine."""
    prompt = np.asarray([3, 14, 15, 92, 65])
    with ContinuousBatchingEngine(
            CFG, params, max_seq=96, max_batch=2, sampling=GREEDY,
            prompt_buckets=(16,)) as plain:
        want = [t[0] for t in plain.generate_stream(prompt, 12)]
    with spec_engine(params, draft_params) as eng:
        got = [t[0] for t in eng.generate_stream(prompt, 12)]
    np.testing.assert_array_equal(want, got)


def test_spec_draft_vocab_mismatch_rejected(params):
    bad = dataclasses.replace(CFG, vocab_size=CFG.vocab_size + 1)
    with pytest.raises(ValueError, match="vocab"):
        ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                 draft_cfg=bad, draft_params=params)


@pytest.mark.slow
def test_spec_sampled_self_draft_accepts_everything(params):
    """Temperature sampling through the slot-loop speculative path with
    draft == target: q == p exactly, so the accept rule (u*q_d < p_d)
    accepts every proposal — exercises the non-greedy q_logits alignment
    and RNG plumbing end-to-end (a row/column misalignment would show as
    acceptance < 1)."""
    samp = SamplingParams(temperature=0.9, top_k=0)
    with ContinuousBatchingEngine(
            CFG, params, max_seq=96, max_batch=2, sampling=samp,
            prompt_buckets=(16,), draft_cfg=CFG, draft_params=params,
            num_draft=4) as eng:
        a = eng.submit([3, 1, 4], 16).wait(timeout=300)
        b = eng.submit([5, 6], 12).wait(timeout=300)
        assert a.shape == (16,) and b.shape == (12,)
        for t in (a, b):
            assert (t >= 0).all() and (t < CFG.vocab_size).all()
        assert eng.stats()["speculative"]["acceptance_rate"] == 1.0


# ---------------------------------------------------------------------------
# prompt-lookup (draft-free) speculation inside the slot loop


def pld_engine(params, **kw):
    return ContinuousBatchingEngine(
        CFG, params, max_seq=160, max_batch=4, sampling=GREEDY,
        prompt_buckets=(16, 64), prompt_lookup=True, num_draft=4, **kw)


def test_pld_single_request_matches_engine(params, oracle):
    """Greedy prompt-lookup batching must be bit-identical to the plain
    engine — the n-gram proposer can be arbitrarily wrong."""
    with pld_engine(params) as eng:
        prompt = [3, 14, 15, 92, 65]
        got = eng.submit(prompt, 12).wait(timeout=300)
        np.testing.assert_array_equal(got, expected(oracle, prompt, 12))
        st = eng.stats()["speculative"]
        assert st["proposer"] == "prompt_lookup" and st["rounds"] >= 1


@pytest.mark.slow
def test_pld_concurrent_and_late_joiner_match(params, oracle):
    with pld_engine(params) as eng:
        first = eng.submit([5, 4, 3, 2], 40)
        deadline = time.monotonic() + 240
        while len(first.tokens) < 5:
            assert time.monotonic() < deadline, "first request stalled"
            time.sleep(0.01)
        second = eng.submit([8, 8, 1], 10)
        third = eng.submit([1, 2, 3, 4, 5, 6], 14)
        np.testing.assert_array_equal(second.wait(timeout=300),
                                      expected(oracle, [8, 8, 1], 10))
        np.testing.assert_array_equal(third.wait(timeout=300),
                                      expected(oracle, [1, 2, 3, 4, 5, 6],
                                               14))
        np.testing.assert_array_equal(first.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 40))


# tier-1 budget: acceptance telemetry keeps a quick-lane rep in the
# §22 adaptive-K test (tests/test_mixed_batching.py)
@pytest.mark.slow
def test_pld_repetitive_prompt_accepts(params):
    """A prompt whose greedy continuation re-uses its own spans gets
    acceptance > 0 through the slot loop (the PLD payoff).  greedy decode
    of the seed-init model loops on a tiled motif, like the standalone
    PromptLookupEngine tests."""
    motif = list(np.arange(16) * 7 % 250)
    oracle64 = InferenceEngine(CFG, params, max_seq=160, sampling=GREEDY)
    want = oracle64.generate(np.asarray([motif * 4]), 48).tokens[0]
    with pld_engine(params) as eng:
        got = eng.submit(motif * 4, 48).wait(timeout=300)
        np.testing.assert_array_equal(got, want)
        assert eng.stats()["speculative"]["acceptance_rate"] > 0


def test_pld_eos_terminates_mid_block(params, oracle):
    prompt = [3, 14, 15, 92, 65]
    ref = expected(oracle, prompt, 12)
    eos = int(ref[4])
    with ContinuousBatchingEngine(
            CFG, params, max_seq=160, max_batch=2, sampling=GREEDY,
            prompt_buckets=(16,), eos_id=eos, prompt_lookup=True,
            num_draft=4) as eng:
        got = eng.submit(prompt, 12).wait(timeout=300)
        np.testing.assert_array_equal(got, list(ref[:5]))


def test_pld_exclusive_with_draft(params):
    with pytest.raises(ValueError, match="exclusive"):
        ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                 prompt_lookup=True, draft_cfg=CFG,
                                 draft_params=params)


# ---------------------------------------------------------------------------
# randomized soak: scheduler races under a mixed workload


@pytest.mark.parametrize("mode", [
    # tier-1 budget: the whole soak family rides the slow lane; the
    # late-joiner/decode-block parity tests are the quick-lane reps
    pytest.param("plain", marks=pytest.mark.slow),
    pytest.param("draft", marks=pytest.mark.slow),
    pytest.param("pld", marks=pytest.mark.slow),
    pytest.param("chunked", marks=pytest.mark.slow),
    pytest.param("chunked-draft", marks=pytest.mark.slow),
])
def test_soak_random_workload(params, draft_params, oracle, mode):
    """30 requests with random lengths, ~20% random cancellations, and
    staggered submission against 3 slots: every surviving request must
    stay bit-exact (fuzz for admission/drain/cancel races in the
    scheduler, across the proposer modes AND chunked admission — the
    chunked modes use longer prompts so the resumable stream, its
    backlog, and cancel-mid-stream all churn)."""
    rng = np.random.default_rng(42)
    kw = {}
    if mode in ("draft", "chunked-draft"):
        kw = dict(draft_cfg=DRAFT_CFG, draft_params=draft_params,
                  num_draft=3)
    elif mode == "pld":
        kw = dict(prompt_lookup=True, num_draft=3)
    if mode.startswith("chunked"):
        kw["prefill_chunk"] = 4
    max_plen = 25 if mode.startswith("chunked") else 9
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=3,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  **kw) as eng:
        reqs = []
        for _ in range(30):
            plen = int(rng.integers(1, max_plen))
            n = int(rng.integers(1, 20))
            prompt = rng.integers(0, 250, size=(plen,)).tolist()
            r = eng.submit(prompt, n)
            if rng.random() < 0.2:
                r.cancel()
            reqs.append((prompt, n, r))
            if rng.random() < 0.3:
                time.sleep(0.005)
        for prompt, n, r in reqs:
            assert r.done.wait(300), "request neither finished nor failed"
            if r.cancelled:
                continue               # partial tokens are fine
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, prompt, n))


# ---------------------------------------------------------------------------
# fused multi-step decode blocks (decode_block > 1)


def test_decode_block_parity_and_late_joiner(params, oracle):
    """decode_block=4 fuses steps per dispatch; greedy output must stay
    bit-exact, including a joiner admitted between blocks and budgets
    that are not block multiples."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=3,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  decode_block=4) as eng:
        first = eng.submit([5, 4, 3, 2], 30)   # not a multiple of 4
        deadline = time.monotonic() + 240
        while len(first.tokens) < 3:
            assert time.monotonic() < deadline, "first request stalled"
            time.sleep(0.005)
        second = eng.submit([8, 8, 1], 9)
        third = eng.submit([1, 2], 6)
        np.testing.assert_array_equal(second.wait(timeout=300),
                                      expected(oracle, [8, 8, 1], 9))
        np.testing.assert_array_equal(third.wait(timeout=300),
                                      expected(oracle, [1, 2], 6))
        np.testing.assert_array_equal(first.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 30))


def test_decode_block_eos_mid_block(params, oracle):
    """A row whose eos lands mid-block truncates exactly there."""
    prompt = [3, 14, 15, 92, 65]
    ref = expected(oracle, prompt, 12)
    eos = int(ref[4])
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  eos_id=eos, decode_block=4) as eng:
        got = eng.submit(prompt, 12).wait(timeout=300)
        np.testing.assert_array_equal(got, list(ref[:5]))


@pytest.mark.parametrize("mode", [
    pytest.param("draft", marks=pytest.mark.slow), "pld"])
def test_decode_block_composes_with_speculation(params, draft_params,
                                                oracle, mode):
    """decode_block in the speculative modes fuses N draft/verify ROUNDS
    per dispatch; greedy output stays bit-exact, including eos landing
    inside a fused block."""
    kw = (dict(draft_cfg=DRAFT_CFG, draft_params=draft_params)
          if mode == "draft" else dict(prompt_lookup=True))
    prompt = [3, 14, 15, 92, 65]
    ref = expected(oracle, prompt, 20)
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  num_draft=3, decode_block=3,
                                  **kw) as eng:
        a = eng.submit(prompt, 20)
        b = eng.submit([8, 8, 1], 9)
        np.testing.assert_array_equal(a.wait(timeout=300), ref)
        np.testing.assert_array_equal(b.wait(timeout=300),
                                      expected(oracle, [8, 8, 1], 9))
        assert eng.stats()["speculative"]["rounds"] >= 2
    eos = int(ref[4])
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  num_draft=3, decode_block=3, eos_id=eos,
                                  **kw) as eng:
        got = eng.submit(prompt, 20).wait(timeout=300)
        np.testing.assert_array_equal(got, list(ref[:5]))


# ---------------------------------------------------------------------------
# chunked admission (prefill_chunk x batch slots)

def test_chunked_admission_matches_engine(params, oracle):
    """A prompt longer than the chunk admits in C-token dispatches; the
    request's tokens are bit-identical to the unchunked engine (chunk
    boundaries only split where K/V is written)."""
    prompt = list(range(2, 25))                    # 23 tokens, C=8 -> 2+tail
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  prefill_chunk=8) as eng:
        got = eng.submit(prompt, 12).wait(timeout=300)
        np.testing.assert_array_equal(got, expected(oracle, prompt, 12))
        st = eng.stats()["chunked_prefill"]
        assert st == {"chunk": 8, "chunks": 2, "interleaved_steps": 0}


def test_chunked_admission_interleaves_decode(params, oracle):
    """While a long prompt admits chunk-by-chunk, in-flight slots keep
    decoding between chunks — and both requests stay bit-exact."""
    long_prompt = list(range(1, 20))               # 19 tokens, C=4 -> 4+tail
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  prefill_chunk=4) as eng:
        first = eng.submit([5, 4, 3, 2], 40)
        deadline = time.monotonic() + 240
        while len(first.tokens) < 5:               # provably mid-flight
            assert time.monotonic() < deadline, "first request stalled"
            time.sleep(0.01)
        second = eng.submit(long_prompt, 10)
        np.testing.assert_array_equal(second.wait(timeout=300),
                                      expected(oracle, long_prompt, 10))
        np.testing.assert_array_equal(first.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 40))
        st = eng.stats()["chunked_prefill"]
        # one interleaved step on the iteration that parks the admission,
        # then one after each of the 4 streamed chunks (the finish
        # iteration clears the admission before stepping)
        assert st["chunks"] == 4 and st["interleaved_steps"] == 5


def test_chunked_admission_composes_with_prefix_cache(params, oracle):
    """Prefix reuse shortens the suffix; what remains still chunks, and
    the divergent-tail request stays exact."""
    base = list(range(2, 34))                      # 32 tokens
    tail = base[:24] + [7, 9, 11, 13, 2, 4, 6, 8]  # 24 shared + 8 new
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  prefill_chunk=8, kv_cache_blocks=16,
                                  kv_block_tokens=8) as eng:
        np.testing.assert_array_equal(
            eng.submit(base, 8).wait(timeout=300),
            expected(oracle, base, 8))
        np.testing.assert_array_equal(
            eng.submit(tail, 8).wait(timeout=300),
            expected(oracle, tail, 8))
        assert eng.kv_cache.stats["hits"] == 1
        # 32/8 = 4 full chunks minus the sampled tail bucket, then the
        # reused-prefix request chunks only its 8-token suffix (0 full
        # chunks — it fits one final dispatch)
        assert eng.stats()["chunked_prefill"]["chunks"] == 3


@pytest.mark.parametrize("mode", [
    pytest.param("draft", marks=pytest.mark.slow),
    # tier-1 budget: test_decode_block_composes_with_speculation[pld]
    # keeps the quick-lane spec-composition rep; the §22 mixed tests
    # pin spec x chunked admission in tier-1
    pytest.param("pld", marks=pytest.mark.slow),
])
def test_chunked_admission_composes_with_speculation(params, draft_params,
                                                     oracle, mode):
    """Chunked target-side admission under both speculative proposers:
    interleaved rounds between chunks, bit-exact output."""
    kw = (dict(draft_cfg=DRAFT_CFG, draft_params=draft_params)
          if mode == "draft" else dict(prompt_lookup=True))
    long_prompt = list(range(3, 22))               # 19 tokens, C=8 -> 2+tail
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  num_draft=3, prefill_chunk=8, **kw) as eng:
        a = eng.submit([5, 4, 3, 2], 30)
        deadline = time.monotonic() + 240
        while len(a.tokens) < 3:
            assert time.monotonic() < deadline, "first request stalled"
            time.sleep(0.01)
        b = eng.submit(long_prompt, 10)
        np.testing.assert_array_equal(b.wait(timeout=300),
                                      expected(oracle, long_prompt, 10))
        np.testing.assert_array_equal(a.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 30))
        assert eng.stats()["chunked_prefill"]["interleaved_steps"] >= 1


def test_chunked_admission_rejects_bad_chunk(params):
    with pytest.raises(ValueError, match="prefill_chunk"):
        ContinuousBatchingEngine(CFG, params, max_seq=96,
                                 prefill_chunk=0)


def test_chunked_admission_cancel_bounded_by_one_chunk(params):
    """A request cancelled while its prompt is still admitting stops at
    the next chunk boundary: the remaining chunks never run and the
    request finishes cleanly with no tokens."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  prefill_chunk=4) as eng:
        orig = eng._paged_chunk_mid
        box, armed = {}, threading.Event()

        def hook(*a, **k):
            out = orig(*a, **k)
            armed.wait(timeout=60)
            box["req"].cancelled = True      # cancel after chunk #1 lands
            return out

        eng._paged_chunk_mid = hook
        box["req"] = eng.submit(list(range(1, 20)), 10)   # 4 full chunks
        armed.set()
        got = box["req"].wait(timeout=300)
        assert got.size == 0 and box["req"].error is None
        assert eng.stats()["chunked_prefill"]["chunks"] == 1


def test_chunked_admission_no_head_of_line_blocking(params, oracle):
    """A short request submitted behind a long chunk-streaming admission
    admits into a free slot and COMPLETES while the long prompt is still
    admitting — chunked admission is resumable scheduler state, not an
    inline loop.  The chunk hook snapshots (chunks_done, short_done) on
    the scheduler thread, so the ordering check is race-free."""
    long_prompt = list(range(1, 42))               # 41 tokens, C=4 -> 10+tail
    short = [8, 8, 1]
    seen = []
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  prefill_chunk=4) as eng:
        orig = eng._paged_chunk_mid
        box = {}

        def hook(*a, **k):
            done = bool(box and box["short"].done.is_set())
            seen.append((eng.chunk_stats["chunks"], done))
            return orig(*a, **k)

        eng._paged_chunk_mid = hook
        a = eng.submit(long_prompt, 6)
        box["short"] = eng.submit(short, 2)
        np.testing.assert_array_equal(a.wait(timeout=300),
                                      expected(oracle, long_prompt, 6))
        np.testing.assert_array_equal(box["short"].wait(timeout=300),
                                      expected(oracle, short, 2))
        assert len(seen) == 10
        # the short request finished while the long admission still had
        # chunks to stream (it needs 2 scheduler iterations; the long
        # admission spans 11)
        assert any(done for _, done in seen[:10])


def test_chunked_admission_streams_while_slots_busy(params, oracle):
    """Chunk streaming needs no free slot: with every slot decoding, a
    long prompt's chunks run anyway (overlapping busy decode) and only
    the final sampling prefill waits for a slot to free."""
    long_prompt = list(range(1, 20))               # 19 tokens, C=4 -> 4+tail
    busy_at_chunk = []
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=1,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  prefill_chunk=4) as eng:
        orig = eng._paged_chunk_mid

        def hook(*a, **k):
            busy_at_chunk.append(eng._slots[0] is not None)
            return orig(*a, **k)

        eng._paged_chunk_mid = hook
        a = eng.submit([5, 4, 3, 2], 40)           # holds the only slot
        b = eng.submit(long_prompt, 6)
        np.testing.assert_array_equal(a.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 40))
        np.testing.assert_array_equal(b.wait(timeout=300),
                                      expected(oracle, long_prompt, 6))
        assert busy_at_chunk == [True] * 4


def test_chunked_admission_failure_fails_only_that_request(params, oracle):
    """A dispatch failure while streaming chunks fails THAT request and
    leaves the engine serving (the per-request error contract every
    other admission dispatch honors)."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  prefill_chunk=4) as eng:
        def boom(*a, **k):
            raise RuntimeError("injected chunk failure")

        eng._paged_chunk_mid = boom
        a = eng.submit([5, 4, 3, 2], 4)            # short: never chunks
        b = eng.submit(list(range(1, 20)), 4)      # chunk-needing
        np.testing.assert_array_equal(a.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 4))
        with pytest.raises(RuntimeError, match="injected chunk failure"):
            b.wait(timeout=300)
        c = eng.submit([8, 8, 1], 3)               # engine still alive
        np.testing.assert_array_equal(c.wait(timeout=300),
                                      expected(oracle, [8, 8, 1], 3))


@pytest.mark.slow
def test_chunked_admission_prefix_hit_passes_streaming_prompt(params,
                                                              oracle):
    """A long prompt whose cached prefix shrinks it to ONE dispatch must
    not wait behind an unrelated chunk stream: classification uses the
    effective suffix, so it admits (and completes) mid-stream."""
    base = list(range(2, 34))                      # 32 tokens -> cached
    hit = base[:28] + [7, 9, 11]                   # 31 tokens, suffix 3
    streamer = list(range(100, 141))               # 41 tokens, C=4 -> 10+tail
    seen = []
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=4,
                                  sampling=GREEDY, prompt_buckets=(16, 64),
                                  prefill_chunk=4, kv_cache_blocks=32,
                                  kv_block_tokens=4) as eng:
        np.testing.assert_array_equal(eng.submit(base, 4).wait(timeout=300),
                                      expected(oracle, base, 4))
        orig = eng._paged_chunk_mid
        box = {}

        def hook(*a, **k):
            done = bool(box and box["hit"].done.is_set())
            seen.append(done)
            return orig(*a, **k)

        eng._paged_chunk_mid = hook
        a = eng.submit(streamer, 4)
        box["hit"] = eng.submit(hit, 2)
        np.testing.assert_array_equal(a.wait(timeout=300),
                                      expected(oracle, streamer, 4))
        np.testing.assert_array_equal(box["hit"].wait(timeout=300),
                                      expected(oracle, hit, 2))
        # the prefix-hit request finished while the streamer still had
        # chunks left (base's 8 chunks ran before the hook armed)
        assert any(seen)
        assert eng.kv_cache.stats["hits"] == 1


# ---------------------------------------------------------------------------
# per-token logprobs (plain slot decoding)

def test_logprobs_match_engine(params, oracle):
    """generate(logprobs=True) scores emitted tokens with the same raw
    log-softmax the plain engine reports."""
    prompt = [3, 14, 15, 92, 65]
    want = oracle.generate(np.asarray(prompt)[None, :], 10, logprobs=True)
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        got = eng.generate(np.asarray([prompt]), 10, logprobs=True)
        np.testing.assert_array_equal(got.tokens, want.tokens)
        np.testing.assert_allclose(got.logprobs, want.logprobs,
                                   rtol=1e-5, atol=1e-5)


def test_logprobs_with_decode_block(params, oracle):
    """The fused multi-step path records block logprobs identically."""
    prompt = [3, 14, 15, 92, 65]
    want = oracle.generate(np.asarray(prompt)[None, :], 9, logprobs=True)
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY, prompt_buckets=(16,),
                                  decode_block=4) as eng:
        got = eng.generate(np.asarray([prompt]), 9, logprobs=True)
        np.testing.assert_array_equal(got.tokens, want.tokens)
        np.testing.assert_allclose(got.logprobs, want.logprobs,
                                   rtol=1e-5, atol=1e-5)


def test_logprobs_rejected_with_speculation(params, draft_params):
    with spec_engine(params, draft_params) as eng:
        with pytest.raises(ValueError, match="logprobs"):
            eng.generate(np.asarray([[1, 2, 3]]), 4, logprobs=True)


# slow lane: HTTP twin — engine-level logprobs parity and the plain HTTP
# batching surface each stay quick
@pytest.mark.slow
def test_http_logprobs_over_batching_backend(params, oracle):
    """POST /generate {"logprobs": true} against the batching backend
    returns per-token logprobs (501 before this surface existed)."""
    import http.client
    import json as _json
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)

    prompt = [3, 14, 15, 92, 65]
    want = oracle.generate(np.asarray(prompt)[None, :], 6, logprobs=True)
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        server = InferenceHTTPServer(eng, port=0, model_name="llama-test")
        server.start()
        try:
            conn = http.client.HTTPConnection(server.host, server.port,
                                              timeout=300)
            conn.request("POST", "/generate",
                         body=_json.dumps({"prompt_ids": [prompt],
                                           "max_new_tokens": 6,
                                           "logprobs": True}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = _json.loads(resp.read())
            conn.close()
            assert resp.status == 200, body
            assert body["tokens"] == want.tokens.tolist()
            np.testing.assert_allclose(body["logprobs"],
                                       want.logprobs, atol=1e-3)
        finally:
            server.shutdown()


# slow lane: spec × logprobs interaction refinement; the logprobs seam
# and the spec modes each keep quick pins of their own
@pytest.mark.slow
def test_logprobs_empty_in_spec_mode(params, draft_params):
    """Speculative requests keep lps EMPTY (no stale admission entry):
    tokens and lps can never silently misalign if the guard is relaxed."""
    with spec_engine(params, draft_params) as eng:
        req = eng.submit([3, 14, 15], 5)
        req.wait(timeout=300)
        assert req.lps == [] and len(req.tokens) == 5


def test_stats_latency_percentiles(params):
    """/stats reports TTFT / e2e / per-token latency percentiles from
    completed requests (the reference's self-measured timer story at the
    batching surface)."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        for _ in range(3):
            eng.submit([5, 4, 3], 4).wait(timeout=300)
        lat = eng.stats()["latency"]
        assert lat["completed"] == 3
        for k in ("ttft_p50_ms", "ttft_p95_ms", "e2e_p50_ms",
                  "e2e_p95_ms", "per_token_p50_ms", "per_token_p95_ms"):
            assert lat[k] > 0
        assert lat["ttft_p50_ms"] <= lat["e2e_p50_ms"]
        eng.reset_stats()
        assert eng.stats()["latency"]["completed"] == 0


@pytest.mark.slow
def test_everything_on_composition(params, draft_params, oracle):
    """The maximal serving stack in ONE engine: tensor parallelism x
    fp8 KV cache x speculative decoding x chunked (resumable) admission
    x fused decode blocks x prefix cache — greedy output bit-identical
    to the plain engine with the same cache dtype.  Every pairwise
    composition has its own test; this pins the full product."""
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh
    from distributed_inference_demo_tpu.runtime.engine import (
        shard_engine_params)

    oracle_fp8 = InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY,
                                 kv_cache_dtype="float8_e4m3fn")
    mesh = make_mesh(MeshConfig(tp=2), jax.devices()[:2])
    sharded = shard_engine_params(params, CFG, mesh)
    dsharded = shard_engine_params(draft_params, DRAFT_CFG, mesh)
    long_prompt = list(range(2, 21))               # 19 tokens, C=8 -> 2+tail
    with ContinuousBatchingEngine(
            CFG, sharded, max_seq=96, max_batch=2, sampling=GREEDY,
            prompt_buckets=(16, 64), mesh=mesh,
            kv_cache_dtype="float8_e4m3fn",
            draft_cfg=DRAFT_CFG, draft_params=dsharded, num_draft=3,
            decode_block=2, prefill_chunk=8, kv_cache_blocks=16,
            kv_block_tokens=4) as eng:
        a = eng.submit([5, 4, 3, 2], 12)
        b = eng.submit(long_prompt, 8)
        np.testing.assert_array_equal(
            a.wait(timeout=600),
            oracle_fp8.generate(np.asarray([[5, 4, 3, 2]]), 12).tokens[0])
        np.testing.assert_array_equal(
            b.wait(timeout=600),
            oracle_fp8.generate(np.asarray([long_prompt]), 8).tokens[0])
        st = eng.stats()
        assert st["chunked_prefill"]["chunks"] == 2
        assert st["speculative"]["rounds"] >= 1
        assert st["latency"]["completed"] == 2


def test_abandoned_stream_frees_slots(params):
    """Closing a stream mid-generation cancels its in-flight requests:
    the slots free after the current step instead of decoding to
    max_new (a disconnected client or a stop-sequence early exit must
    not burn the remaining budget)."""
    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=1,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        gen = eng.generate_stream(np.asarray([[5, 4, 3, 2]]), 60)
        next(gen)
        next(gen)
        gen.close()                      # abandon with ~58 steps left
        follow = eng.submit([8, 8, 1], 3)
        follow.wait(timeout=300)
        # the abandoned request stopped early: total steps stayed
        # below its 60-token budget (cancel lands at the next sweep, so
        # allow generous scheduler run-ahead without flaking)
        assert eng._step_count < 60


# slow lane: HTTP twin — stream-cancel budget release is pinned quick by
# test_abandoned_stream_frees_slots and the stop seam by test_text_e2e
@pytest.mark.slow
def test_http_stop_over_batching_frees_budget(params):
    """POST /generate with stop over the BATCHING backend: the early
    exit closes the stream, which cancels the in-flight request — the
    60-token budget is not decoded after the stop matched."""
    import http.client
    import json as _json
    from distributed_inference_demo_tpu.runtime.http_server import (
        InferenceHTTPServer)

    class EveryIdTok:
        """Toy tokenizer: id -> ' t<id>' (full vocab coverage)."""
        def encode(self, text):
            return [1]

        def decode(self, ids, skip_special=True):
            return "".join(f" t{int(i)}" for i in ids)

    with ContinuousBatchingEngine(CFG, params, max_seq=96, max_batch=2,
                                  sampling=GREEDY,
                                  prompt_buckets=(16,)) as eng:
        prompt = [5, 4, 3, 2]
        # learn the 3rd generated id, then stop on its text
        first = eng.submit(prompt, 4).wait(timeout=300)
        stop_str = f" t{int(first[2])}"
        server = InferenceHTTPServer(eng, port=0, tokenizer=EveryIdTok(),
                                     model_name="llama-test")
        server.start()
        try:
            conn = http.client.HTTPConnection(server.host, server.port,
                                              timeout=300)
            conn.request("POST", "/generate",
                         body=_json.dumps({"prompt_ids": [prompt],
                                           "max_new_tokens": 60,
                                           "stop": [stop_str]}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = _json.loads(resp.read())
            conn.close()
            assert resp.status == 200, body
            assert body["stop_reason"] == ["stop"]
            assert body["tokens"][0] == [int(t) for t in first[:2]]
            # the abandoned stream cancelled its request: nowhere near
            # the 60-token budget was decoded
            assert eng._step_count < 40
        finally:
            server.shutdown()
