"""Parallelism tests on the virtual 8-device CPU mesh: manual TP parity,
SPMD pipeline training step (dp x pp x tp), sharding placement."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_inference_demo_tpu.models import KVCache, StageSpec, get_model_config
from distributed_inference_demo_tpu.models.decoder import (
    init_full_params, stage_forward)
from distributed_inference_demo_tpu.parallel import (
    MeshConfig, make_mesh, shard_params)
from distributed_inference_demo_tpu.parallel.pipeline import (
    make_pipeline_train_step)
from distributed_inference_demo_tpu.parallel.tensor import make_tp_stage_fn


def _full_spec(cfg):
    return StageSpec(0, 1, 0, cfg.num_layers)


@pytest.mark.parametrize("name", ["llama-test", "bloom-test", "mixtral-test"])
def test_manual_tp_matches_single_device(name, devices):
    """shard_map TP forward (tp=2) must reproduce single-device logits."""
    cfg = get_model_config(name)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    spec = _full_spec(cfg)
    ids = jnp.arange(10, dtype=jnp.int32).reshape(1, 10) % cfg.vocab_size
    pos = jnp.arange(10)[None, :]

    ref, _ = stage_forward(params, cfg, spec, ids,
                           KVCache.create(cfg, cfg.num_layers, 1, 32), pos)

    mesh = make_mesh(MeshConfig(tp=2), devices)
    with mesh:
        fn = make_tp_stage_fn(cfg, spec, mesh, params)
        out, cache2 = fn(params, ids, KVCache.create(cfg, cfg.num_layers, 1, 32),
                         pos)
    np.testing.assert_allclose(np.asarray(ref, np.float32),
                               np.asarray(out, np.float32),
                               rtol=2e-4, atol=2e-4)
    assert int(cache2.length) == 10


def test_tp_rejects_indivisible_heads(devices):
    cfg = get_model_config("llama-test")  # nkv=2
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshConfig(tp=4), devices)
    with pytest.raises(ValueError, match="num_kv_heads"):
        make_tp_stage_fn(cfg, _full_spec(cfg), mesh, params)


def test_pipeline_train_step_dp_pp_tp(devices):
    """Full training step over a dp=2 x pp=2 x tp=2 mesh: runs, loss finite,
    params update, and loss decreases over a few steps on a fixed batch."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshConfig(dp=2, pp=2, tp=2), devices)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = make_pipeline_train_step(cfg, mesh, opt, num_microbatches=2)

    rng = jax.random.PRNGKey(1)
    ids = jax.random.randint(rng, (8, 12), 0, cfg.vocab_size, jnp.int32)
    targets = jnp.roll(ids, -1, axis=1).at[:, -1].set(-100)

    with mesh:
        p, s, loss0 = step(params, opt_state, ids, targets)
        losses = [float(loss0)]
        for _ in range(5):
            p, s, loss = step(p, s, ids, targets)
            losses.append(float(loss))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


def test_pipeline_loss_matches_single_device(devices):
    """Pipeline-parallel loss at step 0 == plain single-device loss."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 0,
                             cfg.vocab_size, jnp.int32)
    targets = jnp.roll(ids, -1, axis=1).at[:, -1].set(-100)

    # single-device reference loss
    spec = _full_spec(cfg)
    pos = jnp.broadcast_to(jnp.arange(8), (4, 8))
    logits, _ = stage_forward(params, cfg, spec, ids,
                              KVCache.create(cfg, cfg.num_layers, 4, 8), pos)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    mask = targets != -100
    ll = jnp.take_along_axis(logp, jnp.maximum(targets, 0)[..., None],
                             -1)[..., 0]
    ref_loss = -jnp.sum(jnp.where(mask, ll, 0)) / jnp.sum(mask)

    mesh = make_mesh(MeshConfig(pp=2), devices)
    opt = optax.sgd(0.0)  # lr 0: loss only
    step = make_pipeline_train_step(cfg, mesh, opt, num_microbatches=2)
    with mesh:
        _, _, loss = step(params, opt.init(params), ids, targets)
    np.testing.assert_allclose(float(ref_loss), float(loss), rtol=1e-4)


def test_shard_params_placement(devices):
    """GSPMD placement: wq sharded over tp, norms replicated."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshConfig(dp=2, tp=2), devices)
    sharded = shard_params(params, cfg, mesh)
    wq = sharded.layers["wq"]
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, None, "tp")
    # each device holds half the columns
    shard_shapes = {s.data.shape for s in wq.addressable_shards}
    assert shard_shapes == {(cfg.num_layers, cfg.hidden_size,
                             cfg.num_heads * cfg.head_dim // 2)}
