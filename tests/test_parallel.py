"""Parallelism tests on the virtual 8-device CPU mesh: manual TP parity,
SPMD pipeline training step (dp x pp x tp), sharding placement."""

import os
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from distributed_inference_demo_tpu.parallel.compat import shard_map

from distributed_inference_demo_tpu.models import KVCache, StageSpec, get_model_config
from distributed_inference_demo_tpu.models.decoder import (
    init_full_params, stage_forward)
from distributed_inference_demo_tpu.parallel import (
    MeshConfig, make_mesh, shard_params)
from distributed_inference_demo_tpu.parallel.pipeline import (
    make_pipeline_train_step)
from distributed_inference_demo_tpu.parallel.tensor import make_tp_stage_fn


def _full_spec(cfg):
    return StageSpec(0, 1, 0, cfg.num_layers)


@pytest.mark.parametrize("name", [
    "llama-test",
    # bloom twin — slow lane like the flash/sequence bloom twins; ALiBi
    # under TP shares its shape with the quick llama path
    pytest.param("bloom-test", marks=pytest.mark.slow),
    pytest.param("mixtral-test", marks=pytest.mark.slow)])
def test_manual_tp_matches_single_device(name, devices):
    """shard_map TP forward (tp=2) must reproduce single-device logits."""
    cfg = get_model_config(name)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    spec = _full_spec(cfg)
    ids = jnp.arange(10, dtype=jnp.int32).reshape(1, 10) % cfg.vocab_size
    pos = jnp.arange(10)[None, :]

    ref, _ = stage_forward(params, cfg, spec, ids,
                           KVCache.create(cfg, cfg.num_layers, 1, 32), pos)

    mesh = make_mesh(MeshConfig(tp=2), devices)
    with mesh:
        fn = make_tp_stage_fn(cfg, spec, mesh, params)
        out, cache2 = fn(params, ids, KVCache.create(cfg, cfg.num_layers, 1, 32),
                         pos)
    np.testing.assert_allclose(np.asarray(ref, np.float32),
                               np.asarray(out, np.float32),
                               rtol=2e-4, atol=2e-4)
    assert int(cache2.length) == 10


def test_tp_rejects_indivisible_heads(devices):
    cfg = get_model_config("llama-test")  # nkv=2
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshConfig(tp=4), devices)
    with pytest.raises(ValueError, match="num_kv_heads"):
        make_tp_stage_fn(cfg, _full_spec(cfg), mesh, params)


@pytest.mark.slow
def test_pipeline_train_step_dp_pp_tp(devices):
    """Full training step over a dp=2 x pp=2 x tp=2 mesh: runs, loss finite,
    params update, and loss decreases over a few steps on a fixed batch."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshConfig(dp=2, pp=2, tp=2), devices)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = make_pipeline_train_step(cfg, mesh, opt, num_microbatches=2)

    rng = jax.random.PRNGKey(1)
    ids = jax.random.randint(rng, (8, 12), 0, cfg.vocab_size, jnp.int32)
    targets = jnp.roll(ids, -1, axis=1).at[:, -1].set(-100)

    with mesh:
        p, s, loss0 = step(params, opt_state, ids, targets)
        losses = [float(loss0)]
        for _ in range(5):
            p, s, loss = step(p, s, ids, targets)
            losses.append(float(loss))
    assert np.isfinite(losses).all(), losses
    assert losses[-1] < losses[0], losses


# slow lane: subsumed by test_pipeline_sgd_update_matches_single_device,
# which needs the same loss (and its grads) to match to pass
@pytest.mark.slow
def test_pipeline_loss_matches_single_device(devices):
    """Pipeline-parallel loss at step 0 == plain single-device loss."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(2), (4, 8), 0,
                             cfg.vocab_size, jnp.int32)
    targets = jnp.roll(ids, -1, axis=1).at[:, -1].set(-100)

    # single-device reference loss
    spec = _full_spec(cfg)
    pos = jnp.broadcast_to(jnp.arange(8), (4, 8))
    logits, _ = stage_forward(params, cfg, spec, ids,
                              KVCache.create(cfg, cfg.num_layers, 4, 8), pos)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    mask = targets != -100
    ll = jnp.take_along_axis(logp, jnp.maximum(targets, 0)[..., None],
                             -1)[..., 0]
    ref_loss = -jnp.sum(jnp.where(mask, ll, 0)) / jnp.sum(mask)

    mesh = make_mesh(MeshConfig(pp=2), devices)
    opt = optax.sgd(0.0)  # lr 0: loss only
    step = make_pipeline_train_step(cfg, mesh, opt, num_microbatches=2)
    with mesh:
        _, _, loss = step(params, opt.init(params), ids, targets)
    np.testing.assert_allclose(float(ref_loss), float(loss), rtol=1e-4)


def test_shard_params_placement(devices):
    """GSPMD placement: wq sharded over tp, norms replicated."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    mesh = make_mesh(MeshConfig(dp=2, tp=2), devices)
    sharded = shard_params(params, cfg, mesh)
    wq = sharded.layers["wq"]
    assert wq.sharding.spec == jax.sharding.PartitionSpec(None, None, "tp")
    # each device holds half the columns
    shard_shapes = {s.data.shape for s in wq.addressable_shards}
    assert shard_shapes == {(cfg.num_layers, cfg.hidden_size,
                             cfg.num_heads * cfg.head_dim // 2)}


@pytest.mark.parametrize("pp,tp", [
    pytest.param(2, 1, marks=pytest.mark.slow),
    (1, 2),
    pytest.param(2, 2, marks=pytest.mark.slow),
])
def test_pipeline_sgd_update_matches_single_device(pp, tp, devices):
    """Regression: grads through the shard_map pipeline must match the
    single-device gradient in *scale*, not just direction.  With sgd(1.0)
    the param delta IS the gradient, so any leftover pp/tp scaling (the
    check_vma=False psum-transpose artifact) fails this immediately."""
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    ids = jax.random.randint(jax.random.PRNGKey(3), (4, 8), 0,
                             cfg.vocab_size, jnp.int32)
    targets = jnp.roll(ids, -1, axis=1).at[:, -1].set(-100)

    # single-device reference gradient
    spec = _full_spec(cfg)
    pos = jnp.broadcast_to(jnp.arange(8), (4, 8))

    def ref_loss_fn(p):
        logits, _ = stage_forward(p, cfg, spec, ids,
                                  KVCache.create(cfg, cfg.num_layers, 4, 8),
                                  pos)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        mask = targets != -100
        ll = jnp.take_along_axis(logp, jnp.maximum(targets, 0)[..., None],
                                 -1)[..., 0]
        return -jnp.sum(jnp.where(mask, ll, 0)) / jnp.sum(mask)

    ref_grads = jax.grad(ref_loss_fn)(params)

    # host copies before stepping: the train step donates its params arg
    old = {k: np.asarray(params.layers[k], np.float32)
           for k in ("wq", "w_down")}
    old_embed = np.asarray(params.embed["tokens"], np.float32)

    mesh = make_mesh(MeshConfig(pp=pp, tp=tp), devices)
    opt = optax.sgd(1.0)  # delta == -grad
    step = make_pipeline_train_step(cfg, mesh, opt, num_microbatches=2)
    with mesh:
        new_params, _, _ = step(params, opt.init(params), ids, targets)

    for key in ("wq", "w_down"):
        got = old[key] - np.asarray(new_params.layers[key], np.float32)
        want = np.asarray(ref_grads.layers[key], np.float32)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-5)
    got_embed = old_embed - np.asarray(new_params.embed["tokens"], np.float32)
    np.testing.assert_allclose(
        got_embed, np.asarray(ref_grads.embed["tokens"], np.float32),
        rtol=2e-3, atol=2e-5)


def test_pipeline_quantized_params(devices):
    """'-int8' quantized layer stacks must trace and run through the
    pipeline shard_map (regression: scale spec must keep the pp axis)."""
    from distributed_inference_demo_tpu.ops.quant import quantize_layer_params
    from distributed_inference_demo_tpu.models.base import StageParams

    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    from distributed_inference_demo_tpu.parallel.pipeline import (
        _pp_in_specs, pipeline_apply)
    from jax.sharding import PartitionSpec as P

    qparams = StageParams(layers=quantize_layer_params(params.layers),
                          embed=params.embed, final_norm=params.final_norm,
                          lm_head=params.lm_head)
    mesh = make_mesh(MeshConfig(pp=2, tp=2), devices)
    ids = jax.random.randint(jax.random.PRNGKey(4), (4, 8), 0,
                             cfg.vocab_size, jnp.int32)
    targets = jnp.roll(ids, -1, axis=1).at[:, -1].set(-100)
    ids_mb = ids.reshape(2, 2, 8)
    targets_mb = targets.reshape(2, 2, 8)

    in_specs = _pp_in_specs(qparams, cfg, use_tp=True)
    fwd = shard_map(
        lambda p, i, t: pipeline_apply(cfg, p, i, t, "tp"),
        mesh=mesh, in_specs=(in_specs, P(), P()), out_specs=P(),
        check_vma=False)
    with mesh:
        loss = fwd(qparams, ids_mb, targets_mb)
    assert np.isfinite(float(loss))


@pytest.mark.slow
@pytest.mark.parametrize("pp,tp", [(4, 1), (4, 4)])
def test_grad_scaling_rule_at_4x4(pp, tp):
    """Property test for the derived 1/(pp*tp) gradient rule OUTSIDE the
    previously verified {1,2} envelope (VERDICT r1 item 5): every leaf's
    raw pipeline gradient must be exactly pp*tp x the single-device
    gradient.  Runs tools/grad_scale_probe.py in a subprocess because it
    needs a 16-device virtual mesh (conftest pins this process to 8)."""
    import json
    import os
    import subprocess
    import sys
    from pathlib import Path

    probe = Path(__file__).parent.parent / "tools" / "grad_scale_probe.py"
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    env.pop("XLA_FLAGS", None)   # probe sets its own device count
    proc = subprocess.run(
        [sys.executable, str(probe), "--pp", str(pp), "--tp", str(tp)],
        capture_output=True, text=True, timeout=540, env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    # "uniform" already encodes the probe's 1%/2% per-leaf tolerance;
    # exact float equality on the medians would be flaky across backends
    assert out["uniform"], out


@pytest.mark.parametrize("pp,tp", [
    (2, 1), pytest.param(2, 2, marks=pytest.mark.slow),
    # 4-stage twin — slow lane: deeper-pipeline middle stages stay
    # quick via the 3-stage chaos/elastic loopbacks
    pytest.param(4, 1, marks=pytest.mark.slow)])
def test_pipeline_generate_matches_engine(pp, tp, devices):
    """SPMD circular-pipeline decode (ppermute ring + token lane) must
    reproduce the single-chip engine's greedy tokens for every microbatch
    (VERDICT r1 item 6)."""
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.parallel.pipeline import (
        make_pipeline_generate_fn)
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    greedy = SamplingParams(greedy=True)
    M, b, plen, new = 4, 2, 8, 6
    rng = jax.random.PRNGKey(7)
    ids = jax.random.randint(rng, (M, b, plen), 0, cfg.vocab_size,
                             jnp.int32)

    engine = InferenceEngine(cfg, params, max_seq=32, sampling=greedy)
    want = np.stack([engine.generate(np.asarray(ids[m]), new).tokens
                     for m in range(M)])

    mesh = make_mesh(MeshConfig(pp=pp, tp=tp), devices)
    gen = make_pipeline_generate_fn(cfg, mesh, max_seq=32,
                                    num_new_tokens=new, sampling=greedy)
    with mesh:
        got = np.asarray(gen(params, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)


def test_pipeline_generate_rejects_bad_shapes(devices):
    from distributed_inference_demo_tpu.parallel.pipeline import (
        make_pipeline_generate_fn)

    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    mesh1 = make_mesh(MeshConfig(pp=1), devices)
    with pytest.raises(ValueError, match="pp >= 2"):
        make_pipeline_generate_fn(cfg, mesh1, max_seq=32, num_new_tokens=4)

    mesh = make_mesh(MeshConfig(pp=4), devices)
    gen = make_pipeline_generate_fn(cfg, mesh, max_seq=32, num_new_tokens=4)
    ids = jnp.zeros((2, 1, 8), jnp.int32)   # M=2 < S=4
    with mesh:
        with pytest.raises(ValueError, match="microbatches"):
            gen(params, ids, jax.random.PRNGKey(0))


def test_init_multihost_single_process():
    """init_multihost joins JAX's distributed runtime.  Run in a fresh
    subprocess: initialize() must precede any backend use, which the
    current test process has long since done."""
    import subprocess
    import sys
    import socket

    from distributed_inference_demo_tpu.parallel.mesh import init_multihost

    with pytest.raises(ValueError, match="process topology"):
        init_multihost("127.0.0.1:1", 2, 5)
    with pytest.raises(ValueError, match="local_device_count"):
        init_multihost("127.0.0.1:1", 1, 0, local_device_count=0)

    with socket.socket() as s:          # free port for the coordinator
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; jax.config.update('jax_platforms', 'cpu');"
         "from distributed_inference_demo_tpu.parallel.mesh import "
         "init_multihost;"
         f"init_multihost('127.0.0.1:{port}', 1, 0);"
         "print('NDEV', len(jax.devices()), jax.process_count())"],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    line = [l for l in out.stdout.splitlines() if l.startswith("NDEV")][0]
    assert line.split()[1:] == ["1", "1"] or int(line.split()[1]) >= 1


def test_pipeline_generate_gemma_embed_scale(devices):
    """Regression: the pipeline's embedding path must include gemma's
    sqrt(H) normalizer (it delegates to decoder.embed_tokens — one owner
    — so the manual pipeline cannot drift from single-stage serving)."""
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.parallel.pipeline import (
        make_pipeline_generate_fn)
    from distributed_inference_demo_tpu.runtime import InferenceEngine

    cfg = get_model_config("gemma-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    greedy = SamplingParams(greedy=True)
    ids = jax.random.randint(jax.random.PRNGKey(3), (2, 1, 8), 0,
                             cfg.vocab_size, jnp.int32)
    engine = InferenceEngine(cfg, params, max_seq=32, sampling=greedy)
    want = np.stack([engine.generate(np.asarray(ids[m]), 5).tokens
                     for m in range(2)])
    mesh = make_mesh(MeshConfig(pp=2), devices[:2])
    gen = make_pipeline_generate_fn(cfg, mesh, max_seq=32,
                                    num_new_tokens=5, sampling=greedy)
    with mesh:
        got = np.asarray(gen(params, ids, jax.random.PRNGKey(0)))
    np.testing.assert_array_equal(got, want)
