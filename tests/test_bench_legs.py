"""Smoke the bench legs' code paths at tiny scale on CPU.

A leg bug on the real TPU burns one of the measurement session's three
retry attempts (plus a subprocess budget of up to 40 minutes), so every
leg that can run its full structure on tiny models must prove it here
first.  Numbers are not asserted — only structure and non-error shape.
"""

import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402


@pytest.mark.slow
def test_leg_moe_structure_tiny():
    out = bench._leg_moe(2, 8, 4, moe_model="mixtral-test",
                         dense_model="llama-test")
    assert "error" not in out
    for key in ("moe_bf16", "moe_int8", "dense_equal_active_flops_bf16"):
        assert out[key]["decode_tokens_per_sec"] > 0
        assert out[key]["prefill_tokens_per_sec"] > 0
    assert out["moe_vs_dense_decode"] > 0


def test_bench_engine_latency_percentiles_tiny():
    """The headline legs' TTFT/TPOT block (BENCH_SELF trajectory): real
    percentiles, ordered, from the streamed per-request measurement."""
    out = bench._bench_engine("llama-test", 2, 8, 4, latency=True)
    lat = out["latency"]
    assert lat["requests"] >= 1
    for name in ("ttft", "tpot"):
        p50, p95, p99 = (lat[f"{name}_p{q}_ms"] for q in (50, 95, 99))
        assert p50 is not None and p50 > 0
        assert p50 <= p95 <= p99


@pytest.mark.slow
def test_leg_multimodal_structure_tiny():
    out = bench._leg_multimodal(2, 4, scale="tiny",
                                decoder_model="llama-test")
    assert "error" not in out
    enc = out["vision_encoder_llava15_scale"]
    assert enc["images_per_sec"] > 0
    e2e = out["e2e_image_text_generate"]
    assert e2e["decode_tokens_per_sec"] > 0
    assert e2e["image_tokens"] == enc["patches_per_image"]


@pytest.mark.slow
def test_leg_paged_decode_structure_tiny():
    """The paged_decode leg's full structure (dense-escape-hatch
    reference, paged run, admissible table, primed phase) at CPU-viable
    scale — proves the leg before it can burn a TPU session attempt,
    and pins the leg-level acceptance shape: both HBM numbers present,
    a strictly larger admissible batch at every sequence budget, and
    h2d_bytes == 0 on the primed paged path.  The quick lane runs the
    int8 kv-dtype phase only (one extra engine compile); the full
    int8-vs-int4 ordering rides the slow twin below."""
    out = bench._leg_paged_decode("llama-test", 6, slots=2,
                                  prompt_len=16, max_seq=64,
                                  block_tokens=8, n_req=4,
                                  shared_len=8, kv_dtypes=("int8",))
    assert "error" not in out
    assert out["dense"]["tokens_per_sec"] > 0
    assert out["paged"]["tokens_per_sec"] > 0
    assert out["paged_vs_dense_decode"] > 0
    # the HBM story: reserved (dense) vs actually allocated (paged)
    assert out["dense"]["cache_reserved_bytes"] > 0
    assert 0 < out["paged"]["peak_blocks_in_use"] <= out["paged"][
        "pool_blocks"]
    assert (out["paged"]["peak_bytes_in_use"]
            < out["dense"]["cache_reserved_bytes"])
    # the §14 acceptance gate: at the fixed dense byte budget, paged
    # admits a STRICTLY larger batch at every sequence budget
    for seq in ("4096", "8192", "32768"):
        adm = out["admissible"][seq]
        assert adm["paged_max_batch"] > adm["dense_max_batch"]
        assert adm["budget_bytes"] == out["dense"]["cache_reserved_bytes"]
    # primed wave: radix hits reference device pages, zero H2D
    primed = out["paged_primed"]
    assert primed["hit_rate"] == 1.0
    assert primed["reused_tokens"] >= 4 * 8
    assert primed["h2d_bytes"] == 0
    # the §17 kv-dtype gate: at the SAME fixed byte budget, int8 pages
    # (narrower block_bytes, scale sidecar accounted) admit a strictly
    # larger batch than bf16 pages at every sequence budget — and the
    # wave really decoded against the quantized pool
    q = out["kv_dtype"]["int8"]
    assert q["tokens_per_sec"] > 0
    assert 0 < q["peak_blocks_in_use"]
    assert 0 < q["block_bytes"] < out["paged"]["block_bytes"]
    assert q["scale_block_bytes"] > 0
    assert q["pool_capacity_bytes"] > 0
    for seq in ("4096", "8192", "32768"):
        adm8 = q["admissible"][seq]
        assert adm8["budget_bytes"] == out["dense"]["cache_reserved_bytes"]
        assert (adm8["paged_max_batch"]
                > out["admissible"][seq]["paged_max_batch"])


@pytest.mark.slow
def test_leg_paged_decode_kv_dtype_axis_full():
    """Slow twin of the quick dryrun above: the FULL §17 kv-dtype axis
    (int8 AND int4) with the width ordering pinned — int4 blocks are
    narrower than int8, which are narrower than bf16, and the
    admissible batch grows strictly with each narrowing at every
    sequence budget."""
    out = bench._leg_paged_decode("llama-test", 6, slots=2,
                                  prompt_len=16, max_seq=64,
                                  block_tokens=8, n_req=4,
                                  shared_len=8,
                                  kv_dtypes=("int8", "int4"))
    assert "error" not in out
    q8, q4 = out["kv_dtype"]["int8"], out["kv_dtype"]["int4"]
    assert q8["tokens_per_sec"] > 0 and q4["tokens_per_sec"] > 0
    assert q4["block_bytes"] < q8["block_bytes"] < out["paged"][
        "block_bytes"]
    # int4 carries the wider sidecar (scale + zero-point per token-head)
    assert q4["scale_block_bytes"] > q8["scale_block_bytes"] > 0
    for seq in ("4096", "8192", "32768"):
        bf16_b = out["admissible"][seq]["paged_max_batch"]
        assert (q4["admissible"][seq]["paged_max_batch"]
                > q8["admissible"][seq]["paged_max_batch"]
                > bf16_b)


@pytest.mark.slow
def test_leg_sweep_kv_points_structure_tiny():
    """The sweep's §17 weight-dtype x kv-dtype cross: one batching-
    engine point per pair at the largest batch, each reporting real
    decode throughput against its page pool (int4-KV points included —
    the gather path serves them where the kernel refuses)."""
    out = bench._leg_sweep("llama-test", 16, 4, quants=(False,),
                           batches=(2,), kv_dtypes=("bf16", "int8"))
    assert len(out["points"]) == 1
    kv = out["kv_points"]
    assert [(p["kv_dtype"], p["batch"]) for p in kv] == [("bf16", 2),
                                                         ("int8", 2)]
    for p in kv:
        assert "error" not in p, p
        assert p["engine"] == "batching-paged"
        assert p["decode_tokens_per_sec"] > 0
        assert p["pool_capacity_bytes"] > 0
    assert kv[1]["block_bytes"] < kv[0]["block_bytes"]


@pytest.mark.slow
def test_leg_serving_relative_structure_tiny():
    """The serving_relative leg (VERDICT r5 'Next round' #4): the
    CPU-relative serving ratios — speculative speedup, prompt-lookup
    acceptance, batching throughput-per-slot — with the platform stamp
    that keeps a CPU number from masquerading as a TPU one.  Runs the
    micro variant's shape (the prepass path)."""
    out = bench.run_leg("serving_relative",
                        {"model": "llama-test", "batch": 2,
                         "prompt_len": 32, "new_tokens": 8,
                         "flagship": "llama-test"}, micro=True)
    assert "error" not in out
    assert out["platform"] == "cpu"
    assert out["relative_only"] is True
    assert out["micro"] is True
    assert out["plain_tokens_per_sec"] > 0
    assert out["speculative"]["speedup_vs_plain"] > 0
    assert out["speculative"]["acceptance_rate"] is not None
    assert out["prompt_lookup"]["acceptance_rate"] is not None
    assert out["batching"]["throughput_per_slot"] > 0


def test_long_context_sp_points_structure_tiny(monkeypatch):
    """The sequence-parallel long-context micro points (carried sweep
    satellite): both strategies produce a number (or a per-strategy
    error) — structure proven on the CPU mesh at a shrunken context so
    the 32k TPU shape can't burn a session attempt on a structural
    bug."""
    monkeypatch.setenv("BENCH_LONG_CTX_SP", "256")
    points = bench._long_context_sp_points("llama-test", new=4)
    assert [p["strategy"] for p in points] == ["ring", "ulysses"]
    for p in points:
        assert "error" not in p, p
        assert p["sp"] == 2 and p["context"] == 256
        assert p["tokens_per_sec"] > 0


@pytest.mark.slow
def test_leg_fault_recovery_structure_tiny():
    """The fault_recovery leg's full structure (fault-free reference run,
    injected crash_after, reshard + drain/resume timing) on CPU — the
    tier-1 dryrun the ISSUE-5 bench satellite requires."""
    out = bench._leg_fault_recovery("llama-test", new_tokens=10,
                                    crash_after_msgs=6)
    assert "error" not in out
    assert out["tokens_bit_identical_after_recovery"] is True
    assert out["injected_events"] == ["crash_after"]
    assert out["plan_seed"] == 1234
    assert out["surviving_chain"] == ["s0", "s2"]
    assert out["reshard_seconds"] is not None and out["reshard_seconds"] > 0
    assert (out["crash_to_first_token_seconds"] is not None
            and out["crash_to_first_token_seconds"] > 0)
    assert out["chaos_seconds"] > 0 and out["clean_seconds"] > 0


@pytest.mark.slow
def test_leg_disagg_structure_tiny():
    """The disagg leg's CPU dryrun (the ISSUE-8 acceptance shape):
    TTFT p95 under concurrent decode load for colocated vs
    disaggregated, with the disaggregated configuration WINNING on the
    loopback soak, ``dwt_kvcache_h2d_bytes_total`` staying 0 on the
    decode side for migrated pages (device-to-device adopt, no host
    bounce), migrated/adopted page parity, and zero page leaks on
    both pools."""
    out = bench._leg_disagg("llama-test", n_req=3, prompt_len=128,
                            prefill_chunk=8, max_seq=1024,
                            block_tokens=8)
    assert "error" not in out
    colo, dis = out["colocated"], out["disagg"]
    assert colo["requests"] == dis["requests"] == 3
    assert colo["ttft_p95_ms"] > 0 and dis["ttft_p95_ms"] > 0
    # the headline gate: disaggregation beats colocated TTFT p95 under
    # the saturated-decode load (7 of 8 slots pinned)
    assert out["disagg_wins_ttft_p95"] is True
    assert dis["ttft_p95_ms"] < colo["ttft_p95_ms"]
    # migration really happened, page-for-page
    assert dis["migrated_pages"] > 0
    assert dis["adopted_pages"] == dis["migrated_pages"]
    assert dis["migrated_bytes"] > 0
    # zero host bounce on the decode side; zero leaks on both pools
    assert dis["decode_h2d_bytes"] == 0
    assert dis["decode_pool_leaked_blocks"] == 0
    assert dis["prefill_pool_leaked_blocks"] == 0


@pytest.mark.slow
def test_leg_gateway_routing_structure_tiny():
    """The gateway leg's CPU dryrun (the ISSUE-10 acceptance shape):
    cache-aware routing beats round-robin on BOTH prefix hit-rate and
    TTFT p95 over the grouped shared-prefix workload, and the
    mid-soak replica kill completes every request bit-identically (or
    sheds cleanly) with the eviction counter moving."""
    # shape note: the TTFT-p95 gate is structural only when the
    # full-prefill fraction straddles the percentile — round-robin
    # first-touches every (replica, group) pair (3x2 = 15% of 40
    # requests, above p95), cache-aware only every group (2 = 5%,
    # below it) — so per_group is the lever that de-noises the gate,
    # and prefix_len=300 puts the skipped prefill in the 512-wide
    # bucket where it costs something CPU-visible
    out = bench._leg_gateway_routing("llama-test", groups=2, per_group=20,
                                     prefix_len=300, suffix_len=8,
                                     new_tokens=4, slots=2, max_seq=512,
                                     block_tokens=16, kill_requests=4)
    assert "error" not in out
    rr, aw = out["round_robin"], out["cache_aware"]
    assert rr["requests"] == aw["requests"] == 40
    assert rr["ttft_p95_ms"] > 0 and aw["ttft_p95_ms"] > 0
    # round-robin scatters group members, so its gateway-visible hit
    # rate stays at (near) zero while cache-aware sticks the group
    assert aw["prefix_hit_rate"] > rr["prefix_hit_rate"]
    assert aw["reused_prefix_tokens"] > 0
    # the §16 headline gates, as pinned booleans
    assert out["cache_aware_wins_hit_rate"] is True
    assert out["cache_aware_wins_ttft_p95"] is True
    # the chaos phase: no hangs, no divergent tokens, debounce fired
    kl = out["kill"]
    assert kl["requests"] == 4
    assert kl["hung_or_failed"] == 0
    assert out["kill_zero_hangs"] is True
    assert out["kill_bit_identical"] is True
    assert out["kill_replica_down_moved"] is True
    # the survivor fleet kept serving: at least one replica stayed up
    assert len(kl["survivors"]) >= 1


@pytest.mark.slow
def test_leg_stream_failover_structure_tiny():
    """The stream_failover leg's CPU dryrun (the ISSUE-20 acceptance
    shape): a replica dying mid-soak loses NOTHING — every stream
    completes bit-identically to the unfailed reference via gateway
    resume, the SLO ledger books the replay as a resume pause, the
    documented error-line fallback stays reachable at resume_limit=0,
    and both the survivor and the dead path hand their pages back."""
    out = bench._leg_stream_failover("llama-test", n_req=4,
                                     prompt_len=32, new_tokens=8,
                                     slots=2, max_seq=256,
                                     block_tokens=8, crash_after=2,
                                     seed_victim=2)
    assert "error" not in out
    fo = out["failover"]
    assert fo["requests"] == 4 and fo["completed"] == 4
    assert out["failover_completed_100pct"] is True
    assert out["failover_bit_identical"] is True
    # the victim served >=2 pinned streams, each died 2 tokens in, and
    # every death resumed exactly once on the survivor
    assert out["resume_all_succeeded"] is True
    assert fo["resume_attempts"] >= 2
    assert fo["resume_ttf_p95_ms"] is not None
    assert fo["resume_ttf_p95_ms"] > 0
    # the ledger saw the same resumes the gateway counted, and the
    # timeline decomposition still sums exactly
    assert out["slo_books_resume"] is True
    assert fo["slo_resume_pause_p95_ms"] > 0
    # pre-§23 contract still reachable and documented
    assert out["loss_documented_at_limit_0"] is True
    assert 1 <= out["documented_loss"]["delivered_before_error"] < 8
    # zero leaks on both the dead path and the survivor
    assert out["zero_leak_survivor"] is True
    assert out["zero_leak_victim"] is True


# tier-1 budget: run_leg plumbing keeps its quick reps in the micro-
# variants and dispatch-profile tests; this full-budget structure twin
# rides the slow lane
@pytest.mark.slow
def test_leg_long_context_sp_full_budget_structure(monkeypatch):
    """The promoted >=32k sequence-parallel leg (carried VERDICT
    satellite now at FULL budget in the headline order): run_leg
    dispatches it, both strategies report a number, and the micro
    variant still rides the prepass."""
    monkeypatch.setenv("BENCH_LONG_CTX_SP", "256")
    p = {"model": "llama-test", "batch": 2, "prompt_len": 32,
         "new_tokens": 8, "flagship": "llama-test"}
    out = bench.run_leg("long_context_sp", p, micro=True)
    assert "error" not in out
    assert [pt["strategy"] for pt in out["points"]] == ["ring",
                                                        "ulysses"]
    for pt in out["points"]:
        assert "error" not in pt, pt
        assert pt["sp"] == 2 and pt["tokens_per_sec"] > 0


@pytest.mark.slow
def test_leg_prefix_reuse_structure_tiny():
    """The prefix_reuse leg's full structure (cache-off run, cache-on
    run, hit/reuse/saved report) at CPU-viable scale — the dryrun that
    spends tier-1 minutes so the leg can't burn a TPU session attempt
    on a structural bug."""
    out = bench._leg_prefix_reuse("llama-test", 4, slots=2, n_req=4,
                                  shared_len=12, tail_len=4,
                                  block_tokens=4, kv_blocks=16)
    assert "error" not in out
    # every timed request shares the primed 12-token prefix: all hits
    assert out["hit_rate"] == 1.0
    # 3 whole blocks of shared prefix per request
    assert out["reused_tokens"] == out["requests"] * 12
    assert out["tokens_per_sec_cold"] > 0
    assert out["tokens_per_sec_warm"] > 0
    # wall-delta field is present and finite (sign not asserted: at toy
    # scale scheduler noise can swamp the saved prefill)
    assert isinstance(out["prefill_seconds_saved"], float)
    assert out["blocks_resident"] <= 16


@pytest.mark.slow
def test_leg_tiered_prefix_structure_tiny():
    """The tiered_prefix leg's CPU dryrun (the §21 acceptance shape):
    both phases report TTFT percentiles over the measured revisit
    rounds, promotion h2d bytes move (and the re-prefill phase's stay
    0), blocks demote/spill/promote through all three tiers, the
    greedy revisit tokens are bit-identical across phases, and the
    three-tier zero-leak gate holds at leg end.  The micro shape is
    the run_leg --micro one: a 14-block pool under a 4-group working
    set with a 2-group host ring, so the rest round-trips through the
    disk segment.  The TTFT-p95 WIN is asserted by the full-shape leg
    on device (at this toy scale a 56-token re-prefill costs less than
    the promote dispatch), not here — structure only."""
    out = bench.run_leg("tiered_prefix",
                        {"model": "llama-test", "batch": 2,
                         "prompt_len": 32, "new_tokens": 8,
                         "flagship": "llama-test"}, micro=True)
    assert "error" not in out
    assert out["micro"] is True
    a, b = out["reprefill"], out["tiered"]
    # measured wave = (revisits - 1) rounds x groups
    assert a["requests"] == b["requests"] == 4
    assert a["ttft_p95_ms"] >= a["ttft_p50_ms"] > 0
    assert b["ttft_p95_ms"] >= b["ttft_p50_ms"] > 0
    assert out["tiered_wins_ttft_p95"] in (True, False)
    # the promotion path moved real bytes; nothing else may touch the
    # host bounce (the re-prefill phase pins the counter at 0)
    assert out["promote_h2d_bytes"] > 0
    assert out["reprefill_h2d_bytes"] == 0
    # all three tiers exercised: demotions filled the host ring, the
    # overflow spilled to the disk segment, and revisits promoted back
    # from BOTH
    assert out["demoted_blocks"] > 0
    assert out["spilled_blocks"] > 0
    assert out["promoted_blocks"] > 0
    assert out["tier_hits"]["host"] > 0
    assert out["tier_hits"]["disk"] > 0
    share = out["tier_hit_share"]
    assert abs(share["host"] + share["disk"] - 1.0) < 0.01
    # pinned greedy bit-identity: a promoted prefix is the same cache
    # state, token for token
    assert out["bit_identical"] is True
    # and nothing leaked in any tier
    assert out["three_tier_zero_leak"] is True
    assert out["leaked_blocks"] == {"reprefill": 0, "tiered": 0}


@pytest.mark.slow
def test_leg_decode_fused_structure_tiny():
    """The decode_fused leg's full structure (per-point engines across
    batch x stream_block K, measured dispatches/token) at CPU-viable
    scale — and the leg-level acceptance shape: K=1 pays exactly one
    dispatch per token, K=4 pays 1/K (no eos in the synthetic prompt
    stream, so the ratio is exact)."""
    out = bench._leg_decode_fused("llama-test", 8, 8,
                                  batches=(1, 2), blocks=(1, 4))
    assert "error" not in out
    assert len(out["points"]) == 4
    for pt in out["points"]:
        assert "error" not in pt, pt
        assert pt["tokens"] == 8
        assert pt["decode_tokens_per_sec"] > 0
        K = pt["stream_block"]
        assert pt["host_dispatches"] == (8 if K == 1 else 2)
        assert pt["dispatches_per_token"] == (1.0 if K == 1 else 0.25)
        assert pt["device_loop_steps"] == 8
    assert out["best_decode_tokens_per_sec"] > 0


@pytest.mark.slow
def test_leg_mixed_batching_gates_tiny():
    """The §19 acceptance leg at the de-noised CPU shape: mixed
    token-budget dispatch must strictly beat the alternating baseline
    on aggregate tok/s at equal-or-better TTFT p95, with the 1/K
    structural signature on dispatches/step.  The shape is the one
    run_leg pins for --micro: chunk-heavy prompts through one free
    slot while three background rows decode, all arrivals at once —
    admission pressure covers the whole measured window, which is
    where the baseline's fused-loop suppression costs and the mixed
    packing pays."""
    K = 4
    out = bench._leg_mixed_batching("llama-test", prompt_len=96,
                                    new_tokens=16, slots=4, n_req=8,
                                    prefill_chunk=8, decode_block=K,
                                    arrival_s=0.0, block_tokens=8)
    assert "error" not in out
    assert out["token_budget"] == 4 * K + 2 * 8
    base, mixed = out["baseline"], out["mixed"]
    for mode in (base, mixed):
        assert mode["tokens_per_sec"] > 0
        assert mode["ttft_p95_ms"] is not None
        assert mode["leaked_blocks"] == 0
    # every prompt token of the measured stream went through a packed
    # prefill segment
    assert mixed["prefill_tokens"] == 8 * 96
    assert mixed["mixed_dispatches"] > 0
    assert 0.0 < mixed["budget_utilization"] <= 1.5
    # the structural signature: mixed keeps the fused decode cadence
    # under admission (~1/K dispatches/step); the baseline's
    # suppression drags it toward per-token dispatch
    assert mixed["dispatches_per_step"] <= 1 / K + 0.12, mixed
    assert base["dispatches_per_step"] > mixed["dispatches_per_step"] * 2
    # the acceptance gates (3/3 stable on CPU at this shape)
    assert out["mixed_wins_tokens_per_sec"] is True, (base, mixed)
    assert out["mixed_ttft_p95_le_baseline"] is True, (base, mixed)


@pytest.mark.slow
def test_leg_spec_mixed_structure_tiny():
    """The §22 acceptance leg at the run_leg --micro shape: three
    engines (spec-only serialized chunks, mixed-only packer, fused
    spec x mixed) over the same motif-tiled arrival stream.  On CPU the
    leg must hold its STRUCTURE: the fused arm keeps the 1/K dispatch
    cadence (vs the spec-only arm's ~1/round serialization), carries
    every prompt token through packed segments, reports the §22 shrink
    observables, and leaks nothing in any arm.  The throughput gate is
    asserted (the fused program beats both single-feature arms even
    compute-bound); the TTFT gate is asserted present-and-boolean only
    — spec pricing shrinks per-dispatch prefill room, which CPU pays in
    compute where TPU streams it from HBM."""
    K = 4
    out = bench._leg_spec_mixed("llama-test", prompt_len=96,
                                new_tokens=8, slots=4, n_req=6,
                                prefill_chunk=8, decode_block=K,
                                num_draft=2, arrival_s=0.0,
                                block_tokens=8)
    assert "error" not in out
    # §22 pricing: the default budget prices every slot at
    # (K_row + 1) * decode_block plus two chunks of prefill room
    assert out["token_budget"] == 4 * (2 + 1) * K + 2 * 8
    spec_only, mixed_only, fused = (out["spec_only"], out["mixed_only"],
                                    out["spec_mixed"])
    for mode in (spec_only, mixed_only, fused):
        assert mode["tokens_per_sec"] > 0
        assert mode["ttft_p95_ms"] is not None
        assert mode["leaked_blocks"] == 0
    # every prompt token of the measured stream went through a packed
    # prefill segment in BOTH mixed arms
    assert mixed_only["prefill_tokens"] == 6 * 96
    assert fused["prefill_tokens"] == 6 * 96
    assert 0.0 < fused["budget_utilization"] <= 1.5
    # the structural signature: the fused program keeps the 1/K fused
    # cadence WITH speculation aboard; the spec-only arm pays ~one
    # dispatch per speculative round
    assert fused["dispatches_per_step"] <= 1 / K + 0.12, fused
    assert (spec_only["dispatches_per_step"]
            > fused["dispatches_per_step"] * 2)
    # §22 shrink observables ride both spec arms
    for arm in (spec_only, fused):
        sp = arm["spec"]
        assert sp["drafted"] > 0 and sp["adaptive"] is True
        assert set(sp["k_row_buckets"]) == {"1", "2"}
    # the background rows survive the window (a row finishing
    # mid-window would dump its warmup-compile TTFT into the reservoir
    # and zero its arm's background tokens)
    assert fused["background_tokens"] > 0
    assert sum(fused["spec"]["k_row_buckets"].values()) == 3
    # the throughput gate holds even compute-bound; the TTFT gate is a
    # measured boolean whose truth is a device property
    assert out["spec_mixed_wins_tokens_per_sec"] is True, out
    assert isinstance(out["ttft_p95_le_mixed_only"], bool)


def test_run_leg_stamps_dispatch_profile_extras(monkeypatch):
    """The §20 bench satellite's CPU dryrun: a headline-order leg run
    through run_leg stamps the ``dispatch_profile`` extras block —
    per-signature p50/p95 from the sampled dispatch profiler plus the
    compile ledger — so BENCH_SELF r06+ artifacts carry the cost
    observatory without a TPU session proving the plumbing first.
    Sampling is forced to every dispatch so the tiny micro shape still
    banks samples deterministically."""
    from distributed_inference_demo_tpu.telemetry import profiling
    monkeypatch.setenv("DWT_PROFILE_SAMPLE_N", "1")
    profiling.reset_observatory()
    try:
        p = {"model": "llama-test", "batch": 8, "prompt_len": 64,
             "new_tokens": 128, "flagship": "llama-test"}
        out = bench.run_leg("decode_fused", p, micro=True)
        assert "error" not in out
        dp = out["dispatch_profile"]
        assert dp["sample_n"] == 1
        # the K=4 point runs the fused loop: its signature carries the
        # program class, pow2 batch bucket, chunk K and kv dtype
        sigs = dp["signatures"]
        assert any(s.startswith("decode_loop|b1|c4|") for s in sigs), sigs
        for entry in sigs.values():
            assert entry["samples"] >= 1
            assert entry["dispatches"] >= entry["samples"]
            assert entry["p95_ms"] >= entry["p50_ms"] >= 0.0
        # the compile ledger saw the engine's jitted programs compile
        comp = dp["compile"]
        assert comp["decode_loop"]["compiles"] >= 1
        assert comp["decode_loop"]["compile_seconds"] > 0
        # un-budgeted programs must not feed recompile_storm
        assert comp["decode_loop"]["variant_budget"] is None
    finally:
        monkeypatch.delenv("DWT_PROFILE_SAMPLE_N", raising=False)
        profiling.reset_observatory()


def test_run_leg_micro_variants_stamp_and_shrink():
    """--micro runs the same leg structure at the smallest meaningful
    shape and stamps the result so a micro number can never masquerade
    as a full-budget measurement."""
    p = {"model": "llama-test", "batch": 8, "prompt_len": 64,
         "new_tokens": 128, "flagship": "llama-test"}
    shrunk = bench.micro_shape(p)
    assert (shrunk["batch"], shrunk["prompt_len"],
            shrunk["new_tokens"]) == (2, 32, 8)
    out = bench.run_leg("decode_fused", p, micro=True)
    assert out["micro"] is True
    assert out["micro_shape"] == {"batch": 2, "prompt_len": 32,
                                  "new_tokens": 8}
    assert "error" not in out
    # the micro decode_fused variant runs the reduced point grid
    assert {(pt["batch"], pt["stream_block"])
            for pt in out["points"]} == {(1, 1), (1, 4)}
