"""Paged KV layout on the ContinuousBatchingEngine (docs/DESIGN.md §11).

The acceptance oracle is the same one the dense engine answers to:
greedy tokens must be bit-identical to a lone InferenceEngine run —
cold AND radix-primed — because the paged layout is a memory
architecture, never a semantics change.  On top of parity: the
block-leak invariant (after every request finishes, cancels, or fails,
the only allocated pages are the radix tree's), zero H2D on primed
admissions, and — since the scheduler went paged-NATIVE (docs/DESIGN.md
§14) — the speculative slot proposers riding the pool and the loud
rejection of the deleted dense batch cache.

Runs on CPU through the XLA-gather fallback — the same code path the
TPU kernel's auto-dispatch falls back to, so tier-1 exercises the
production control flow end to end.
"""

import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def oracle(params):
    return InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY)


def expected(oracle, prompt, n):
    return oracle.generate(np.asarray(prompt)[None, :], n).tokens[0]


def paged_engine(params, **kw):
    kw.setdefault("max_seq", 96)
    kw.setdefault("max_batch", 4)
    kw.setdefault("sampling", GREEDY)
    kw.setdefault("prompt_buckets", (16,))
    kw.setdefault("kv_block_tokens", 8)
    return ContinuousBatchingEngine(CFG, params, kv_layout="paged", **kw)


def assert_no_leak(eng):
    """All pages either free or radix-tree-owned: nothing leaked by a
    completed/cancelled/failed request, and no lease pin outlives its
    request (leased_nodes counts live pins)."""
    mgr = eng.kv_cache
    assert mgr.used_blocks == mgr.tree.block_count, (
        mgr.used_blocks, mgr.tree.block_count)
    assert mgr.debug_state()["leased_nodes"] == 0


@pytest.mark.slow
def test_cold_parity_concurrent_requests(params, oracle):
    prompts = [[3, 14, 15], [9, 2, 6, 5, 3, 5], [1], [7, 7, 7, 7]]
    ns = [10, 14, 8, 12]
    with paged_engine(params) as eng:
        reqs = [eng.submit(p, n) for p, n in zip(prompts, ns)]
        for p, n, r in zip(prompts, ns, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, n))
        assert eng.stats()["kv_layout"] == "paged"
        assert_no_leak(eng)


@pytest.mark.slow
def test_primed_parity_and_zero_h2d(params, oracle):
    """Radix-primed admission: the second request block-table-references
    the first one's pages — identical greedy tokens, h2d_bytes == 0
    (the paged path never gathers block bytes through the host)."""
    shared = list(np.arange(16) + 40)        # two whole 8-token blocks
    pa, pb = shared + [1, 2, 3], shared + [4, 5, 6]
    with paged_engine(params) as eng:
        ra = eng.submit(pa, 10)
        np.testing.assert_array_equal(ra.wait(timeout=300),
                                      expected(oracle, pa, 10))
        rb = eng.submit(pb, 10)
        np.testing.assert_array_equal(rb.wait(timeout=300),
                                      expected(oracle, pb, 10))
        snap = eng.kv_cache.snapshot()
        assert snap["hits"] >= 1
        assert snap["partial_hit_tokens"] >= 16
        assert snap["h2d_bytes"] == 0
        assert snap["device_resident_bytes"] > 0
        assert_no_leak(eng)


def test_oversubscribed_pool_requeues_and_completes(params, oracle):
    """More demand than pages: admissions wait for completions to free
    pages (the paged twin of waiting for a slot) and still come out
    exact.  4 slots x 3 blocks/request > 8 pool blocks."""
    prompts = [[i + 1, i + 2, i + 3, i + 4] for i in range(6)]
    with paged_engine(params, max_seq=64, kv_cache_blocks=8) as eng:
        reqs = [eng.submit(p, 18) for p in prompts]
        for p, r in zip(prompts, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, 18))
        assert_no_leak(eng)


def test_cancel_and_close_free_blocks(params):
    with paged_engine(params, max_batch=2) as eng:
        r = eng.submit([5, 4, 3, 2], 60)
        deadline = time.monotonic() + 240
        while len(r.tokens) < 3:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        r.cancel()
        r.wait(timeout=120)
        deadline = time.monotonic() + 30
        while eng.kv_cache.used_blocks != eng.kv_cache.tree.block_count:
            assert time.monotonic() < deadline, "cancel leaked pages"
            time.sleep(0.02)
        assert_no_leak(eng)
        # a request failed at submit-time validation must not leak either
        with pytest.raises(ValueError):
            eng.submit([], 4)
        assert_no_leak(eng)


def test_failed_request_frees_blocks(params):
    """A request the scheduler fails mid-flight (close() drain) releases
    its pages like a completed one."""
    eng = paged_engine(params, max_batch=1)
    slow = eng.submit([9, 9, 9], 80)
    queued = eng.submit([8, 8, 8], 80)     # waits for the only slot
    while len(slow.tokens) < 2:
        time.sleep(0.01)
    eng.close()                            # drains: fails in-flight+queued
    with pytest.raises(RuntimeError):
        queued.wait(timeout=60)
    assert_no_leak(eng)


def test_submit_rejects_request_larger_than_pool(params):
    with paged_engine(params, max_batch=1, kv_cache_blocks=2) as eng:
        with pytest.raises(ValueError, match="paged pool"):
            eng.submit(list(range(1, 30)), 30)


@pytest.mark.slow
def test_paged_speculative_slot_modes_and_leak(params, oracle):
    """The §11 rejection matrix is DISSOLVED (docs/DESIGN.md §14): the
    speculative slot proposers run on the page pool — prompt-lookup
    verifies through the frozen tables, the draft model additionally
    reserves (and drains) its own scratch page pool — with greedy
    parity against the plain engine and zero leaked pages."""
    with paged_engine(params, max_batch=2, prompt_lookup=True,
                      num_draft=3) as eng:
        p = [5, 4, 3, 2, 5, 4, 3]
        np.testing.assert_array_equal(eng.submit(p, 9).wait(timeout=300),
                                      expected(oracle, p, 9))
        assert_no_leak(eng)
    cfg8 = get_model_config("llama-test-int8")
    params8 = init_full_params(jax.random.PRNGKey(0), cfg8,
                               quantize=True)
    with paged_engine(params, max_batch=2, draft_cfg=cfg8,
                      draft_params=params8, num_draft=3) as eng:
        p = [5, 4, 3, 2]
        np.testing.assert_array_equal(eng.submit(p, 9).wait(timeout=300),
                                      expected(oracle, p, 9))
        assert_no_leak(eng)
        # the draft half of the leak invariant: scratch pages drained
        assert eng._dmgr.used_blocks == 0


def test_batching_rejects_dense_env_and_flag(params, monkeypatch):
    """kv_layout='dense' (flag or env) must fail loudly EVERYWHERE:
    the escape hatch is removed (docs/DESIGN.md §14) and a knob
    promising it must never silently run paged.  The error names the
    removal, not a generic unknown-layout complaint."""
    with pytest.raises(ValueError, match="REMOVED"):
        ContinuousBatchingEngine(CFG, params, max_seq=64,
                                 sampling=GREEDY, kv_layout="dense")
    monkeypatch.setenv("DWT_KV_LAYOUT", "dense")
    with pytest.raises(ValueError, match="REMOVED"):
        ContinuousBatchingEngine(CFG, params, max_seq=64,
                                 sampling=GREEDY)
    # the single-request engines reject it the same way — no engine
    # honors the removed layout
    with pytest.raises(ValueError, match="REMOVED"):
        InferenceEngine(CFG, params, max_seq=64, sampling=GREEDY)
    monkeypatch.delenv("DWT_KV_LAYOUT")
    eng = InferenceEngine(CFG, params, max_seq=64, sampling=GREEDY)
    assert eng.kv_layout == "paged"


def test_decode_block_fused_parity(params, oracle):
    """Fused multi-step decode over the paged cache: tables frozen for
    the block, finished rows' overshoot writes drop via sentinels."""
    ps = [[5, 4, 3, 2], [8, 8, 1]]
    with paged_engine(params, max_batch=2, decode_block=4) as eng:
        reqs = [eng.submit(p, 13) for p in ps]
        for p, r in zip(ps, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, 13))
        assert_no_leak(eng)


@pytest.mark.slow
def test_chunked_admission_parity(params, oracle):
    """prefill_chunk composes with paged: chunks stream into the dense
    temp row, the finished row scatters into this request's own pages."""
    long_p = list(np.arange(40) % 50 + 1)
    with paged_engine(params, max_batch=2, prompt_buckets=(16, 64),
                      prefill_chunk=16) as eng:
        r = eng.submit(long_p, 10)
        np.testing.assert_array_equal(r.wait(timeout=300),
                                      expected(oracle, long_p, 10))
        assert eng.chunk_stats["chunks"] >= 1
        assert_no_leak(eng)
