"""Mixed prefill+decode token-budget dispatch (docs/DESIGN.md §19).

The ISSUE-15 acceptance, pinned:

- EXACTNESS: greedy and sampled streams out of the mixed dispatch are
  bit-identical to the serialized interleave (same chunk boundaries,
  same rng split order) — mixed packing is a throughput change, never
  a semantics change;
- decode fusion SURVIVES admission: with prefill chunks in flight the
  measured dispatches/step ratio stays ≈ 1/K (the pre-§19 fuse
  suppression during admission is gone);
- the paged prefill path writes prompt K/V straight into the page
  pool: ``h2d_bytes`` stays 0 across cold admission (the dense
  temp-row gather→prefill→scatter round trip is deleted);
- a dispatch failure with packed admissions fails THOSE requests and
  leaves the engine serving, with zero leaked pages
  (``used == tree.block_count``);
- the mixed stats fragment (dispatches / prefill_tokens /
  budget_utilization) and ``pending_prefill_tokens`` surface through
  ``stats()``.

Runs on CPU through the XLA-gather fallback — the same control flow
the TPU prefill kernel's auto-dispatch falls back to.
"""

import dataclasses
import sys
import time
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)

CFG = get_model_config("llama-test")
DRAFT_CFG = dataclasses.replace(CFG, num_layers=2)
GREEDY = SamplingParams(greedy=True)


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


@pytest.fixture(scope="module")
def draft_params():
    # different seed AND depth: a genuinely different (bad) proposer
    return init_full_params(jax.random.PRNGKey(1), DRAFT_CFG)


@pytest.fixture(scope="module")
def oracle(params):
    return InferenceEngine(CFG, params, max_seq=96, sampling=GREEDY)


def expected(oracle, prompt, n):
    return oracle.generate(np.asarray(prompt)[None, :], n).tokens[0]


def mixed_engine(params, **kw):
    kw.setdefault("max_seq", 96)
    kw.setdefault("max_batch", 4)
    kw.setdefault("sampling", GREEDY)
    kw.setdefault("prompt_buckets", (16, 48))
    kw.setdefault("kv_block_tokens", 8)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("decode_block", 4)
    kw.setdefault("mixed_token_budget", 24)
    return ContinuousBatchingEngine(CFG, params, **kw)


def assert_no_leak(eng):
    mgr = eng.kv_cache
    assert mgr.used_blocks == mgr.tree.block_count, (
        mgr.used_blocks, mgr.tree.block_count)
    assert mgr.debug_state()["leased_nodes"] == 0


@pytest.mark.quick
def test_mixed_cold_parity_stats_and_zero_h2d(params, oracle):
    """Concurrent cold requests through the mixed loop: greedy tokens
    bit-identical to the one-shot oracle, every prompt token prefilled
    INSIDE mixed dispatches, zero bytes gathered through the host, no
    page leaked."""
    prompts = [[3, 14, 15], list(range(2, 24)), [9, 2, 6, 5, 3, 5],
               list(range(40, 75))]
    ns = [10, 12, 8, 9]
    with mixed_engine(params) as eng:
        reqs = [eng.submit(p, n) for p, n in zip(prompts, ns)]
        for p, n, r in zip(prompts, ns, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, n))
        st = eng.stats()
        assert st["mixed"]["token_budget"] == 24
        assert st["mixed"]["dispatches"] > 0
        # cold + disjoint prompts: every prompt token went through a
        # packed prefill segment
        assert (st["mixed"]["prefill_tokens"]
                == sum(len(p) for p in prompts))
        u = st["mixed"]["budget_utilization"]
        # the stall-free floor (>= 1 segment per dispatch) may nudge a
        # packed step past the budget; utilization stays near (0, 1]
        assert u is not None and 0.0 < u <= 1.5
        assert st["pending_prefill_tokens"] == 0
        assert eng.kv_cache.snapshot()["h2d_bytes"] == 0
        assert_no_leak(eng)


@pytest.mark.quick
def test_mixed_sampled_stream_bit_identical_to_serialized(params):
    """The rng contract: one split per packed final in pack order, one
    decode split per decoding dispatch — the serialized path's exact
    spend, so SAMPLED streams (tokens and logprobs) match bit-for-bit
    across sequential requests."""
    samp = SamplingParams(greedy=False, temperature=0.9, top_k=40)

    def run(**kw):
        with ContinuousBatchingEngine(
                CFG, params, max_seq=96, max_batch=4, sampling=samp,
                seed=7, prompt_buckets=(16, 48), kv_block_tokens=8,
                prefill_chunk=8, decode_block=4, **kw) as eng:
            outs = []
            for p, n in ((list(range(3, 30)), 8), ([9, 8, 7, 6], 6)):
                r = eng.submit(p, n)
                outs.append((list(r.wait(timeout=300)), list(r.lps)))
            return outs

    assert run() == run(mixed_token_budget=24)


@pytest.mark.quick
def test_decode_fusion_survives_admission(params, oracle):
    """The acceptance headline: submit a chunk-streaming prompt while a
    row decodes — chunks pack INTO decode dispatches
    (interleaved_steps > 0) and dispatches/step stays ≈ 1/K instead of
    collapsing to per-token suppression."""
    K = 4
    with mixed_engine(params, max_batch=2) as eng:
        a = eng.submit([5, 4, 3, 2], 36)
        deadline = time.monotonic() + 60
        while len(a.tokens) < 2:
            assert time.monotonic() < deadline, "row A never started"
            time.sleep(0.002)
        b = eng.submit(list(range(1, 36)), 8)    # 4 chunks + final
        np.testing.assert_array_equal(a.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 36))
        np.testing.assert_array_equal(
            b.wait(timeout=300), expected(oracle, list(range(1, 36)), 8))
        assert eng.chunk_stats["interleaved_steps"] >= 1
        ls = eng.loop_stats
        assert ls["device_loop_steps"] > 0
        ratio = ls["host_dispatches"] / ls["device_loop_steps"]
        # exact 1/K plus a margin for early-exit tail blocks at each
        # request's end; the suppressed path would measure ≈ 1.0
        assert ratio <= 1 / K + 0.12, ls


@pytest.mark.quick
def test_mixed_admission_failure_fails_request_not_engine(params, oracle):
    """A dispatch failure while admissions are packed fails THOSE
    requests (the serialized admission contract) and leaves the engine
    serving with zero leaked pages."""
    with mixed_engine(params, max_batch=2) as eng:
        orig = eng._mixed_step
        state = {"armed": True}

        def boom(*a, **k):
            if state["armed"]:
                state["armed"] = False
                raise RuntimeError("injected mixed failure")
            return orig(*a, **k)

        eng._mixed_step = boom
        b = eng.submit(list(range(1, 20)), 6)
        with pytest.raises(RuntimeError, match="injected mixed failure"):
            b.wait(timeout=300)
        assert b.error is not None
        c = eng.submit([8, 8, 1], 3)
        np.testing.assert_array_equal(c.wait(timeout=300),
                                      expected(oracle, [8, 8, 1], 3))
        assert eng.stats()["pending_prefill_tokens"] == 0
        assert_no_leak(eng)


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", [None, "int8", "int4"])
@pytest.mark.parametrize("chunk,budget", [(4, 8), (8, 24), (16, 32)])
def test_mixed_matches_serialized_property_sweep(params, kv_dtype,
                                                 chunk, budget):
    """Property sweep (chunk sizes x budgets x eos-mid-decode x
    quantized pages): concurrent greedy streams out of the mixed loop
    are bit-identical to the serialized interleave — quantized pages
    included, because both modes write the SAME chunk values at the
    SAME page positions (quantization points coincide) — and every
    run ends leak-free."""
    prompts = [(list(range(3, 30)), 10), ([9, 8, 7, 6], 8),
               (list(range(50, 85)), 6)]

    def run(eos_id, mixed):
        kw = {"mixed_token_budget": budget} if mixed else {}
        with ContinuousBatchingEngine(
                CFG, params, max_seq=96, max_batch=4, sampling=GREEDY,
                seed=3, prompt_buckets=(16, 48), kv_block_tokens=8,
                prefill_chunk=chunk, decode_block=4, eos_id=eos_id,
                kv_dtype=kv_dtype, **kw) as eng:
            reqs = [eng.submit(p, n) for p, n in prompts]
            outs = [list(r.wait(timeout=300)) for r in reqs]
            assert_no_leak(eng)
            return outs

    base = run(None, mixed=False)
    assert run(None, mixed=True) == base
    # an eos taken from a real stream ends one request mid-decode while
    # the others still admit/decode — truncation points must coincide
    eos = int(base[0][4])
    assert run(eos, mixed=True) == run(eos, mixed=False)


# ---------------------------------------------------------------------------
# §22: speculation inside the mixed dispatch (docs/DESIGN.md §22)
# ---------------------------------------------------------------------------


def spec_kw(proposer, draft_params=None, num_draft=3, **extra):
    if proposer == "pld":
        kw = dict(prompt_lookup=True, num_draft=num_draft)
    else:
        kw = dict(draft_cfg=DRAFT_CFG, draft_params=draft_params,
                  num_draft=num_draft)
    kw.update(extra)
    return kw


def assert_spec_idle(eng):
    """§22 zero-leak extension: the draft scratch pool holds no pages
    when no request is in flight."""
    if eng._dmgr is not None:
        assert eng._dmgr.used_blocks == 0, eng._dmgr.used_blocks


@pytest.mark.quick
@pytest.mark.parametrize("proposer", [
    "pld",
    # tier-1 budget: the draft proposer keeps quick-lane coverage via
    # the sampled and adaptive-shrink tests; this greedy twin rides
    # the slow lane with the property sweep
    pytest.param("draft", marks=pytest.mark.slow),
])
def test_spec_mixed_greedy_parity_and_zero_leak(params, draft_params,
                                                oracle, proposer):
    """§22 headline at greedy: speculative rows packed into the SAME
    mixed dispatch as prefill chunks and plain decode, adaptive K live,
    concurrent submissions — and the streams are still bit-identical to
    the one-shot oracle.  Both proposers; draft scratch pool returns to
    zero pages at idle."""
    prompts = [[3, 14, 15], list(range(2, 24)), [9, 2, 6, 5, 3, 5]]
    ns = [10, 12, 8]
    with mixed_engine(params, **spec_kw(proposer, draft_params)) as eng:
        reqs = [eng.submit(p, n) for p, n in zip(prompts, ns)]
        for p, n, r in zip(prompts, ns, reqs):
            np.testing.assert_array_equal(r.wait(timeout=300),
                                          expected(oracle, p, n))
        sp = eng.stats()["speculative"]
        assert sp["drafted"] > 0
        assert sp["adaptive"] is True
        assert eng.stats()["mixed"]["dispatches"] > 0
        assert_no_leak(eng)
        assert_spec_idle(eng)


@pytest.mark.quick
def test_spec_mixed_sampled_bit_identical_to_serialized(params,
                                                        draft_params):
    """§22 rng contract: the fused draft/verify dispatch spends rng
    exactly like the serialized spec schedule, so SAMPLED streams
    (tokens and logprobs) match bit-for-bit.  K_row is pinned — the
    adaptive controller feeds back measured wall-clock acceptance, which
    is not part of the schedule being compared."""
    samp = SamplingParams(greedy=False, temperature=0.9, top_k=40)

    def run(**kw):
        with ContinuousBatchingEngine(
                CFG, params, max_seq=96, max_batch=4, sampling=samp,
                seed=7, prompt_buckets=(16, 48), kv_block_tokens=8,
                prefill_chunk=8, decode_block=4, draft_cfg=DRAFT_CFG,
                draft_params=draft_params, num_draft=3,
                spec_adaptive=False, **kw) as eng:
            outs = []
            for p, n in ((list(range(3, 30)), 8), ([9, 8, 7, 6], 6)):
                r = eng.submit(p, n)
                outs.append((list(r.wait(timeout=300)), list(r.lps)))
            return outs

    assert run() == run(mixed_token_budget=24)


@pytest.mark.parametrize("kv_dtype", [
    # tier-1 budget: both quantized reps ride the slow lane — the
    # quick-lane bf16 greedy parity test pins the same fused-program
    # seam, and the §17 suite pins quantized-page exactness itself
    pytest.param("int8", marks=pytest.mark.slow),
    pytest.param("int4", marks=pytest.mark.slow),
])
def test_spec_mixed_quantized_greedy_matches_serialized(params, kv_dtype):
    """Quick quantized rep (the full cross product runs in the slow
    sweep): greedy spec x mixed over int8/int4 pages matches the
    serialized spec schedule on the SAME page dtype — verify reads and
    draft proposals see identically-quantized history in both modes."""

    def run(mixed):
        kw = {"mixed_token_budget": 24} if mixed else {}
        with ContinuousBatchingEngine(
                CFG, params, max_seq=96, max_batch=4, sampling=GREEDY,
                prompt_buckets=(16, 48), kv_block_tokens=8,
                prefill_chunk=8, decode_block=4, kv_dtype=kv_dtype,
                prompt_lookup=True, num_draft=3, **kw) as eng:
            reqs = [eng.submit(p, n)
                    for p, n in ((list(range(3, 24)), 8), ([9, 8, 7], 6))]
            outs = [list(r.wait(timeout=300)) for r in reqs]
            assert_no_leak(eng)
            return outs

    assert run(mixed=True) == run(mixed=False)


@pytest.mark.quick
def test_spec_dispatch_ratio_survives_admission(params, oracle):
    """§22 acceptance: dispatches/step stays ≈ 1/K with speculation
    armed WHILE a chunked prompt admits — the spec row keeps its fused
    cadence inside the packed program instead of being suppressed."""
    K = 4
    with mixed_engine(params, max_batch=2, prompt_lookup=True,
                      num_draft=3, mixed_token_budget=40) as eng:
        a = eng.submit([5, 4, 3, 2], 36)
        deadline = time.monotonic() + 60
        while len(a.tokens) < 2:
            assert time.monotonic() < deadline, "row A never started"
            time.sleep(0.002)
        b = eng.submit(list(range(1, 36)), 8)
        np.testing.assert_array_equal(a.wait(timeout=300),
                                      expected(oracle, [5, 4, 3, 2], 36))
        np.testing.assert_array_equal(
            b.wait(timeout=300), expected(oracle, list(range(1, 36)), 8))
        assert eng.chunk_stats["interleaved_steps"] >= 1
        sp = eng.stats()["speculative"]
        assert sp["drafted"] > 0
        ls = eng.loop_stats
        assert ls["device_loop_steps"] > 0
        ratio = ls["host_dispatches"] / ls["device_loop_steps"]
        # accepted drafts only push the ratio further BELOW the plain
        # fused bound; the suppressed path would measure ≈ 1.0
        assert ratio <= 1 / K + 0.12, ls
        assert_no_leak(eng)


@pytest.mark.quick
def test_spec_adaptive_k_shrinks_on_low_acceptance(params, draft_params,
                                                   oracle):
    """Adaptive K_row feedback: a draft model that disagrees with the
    target drives EWMA acceptance down, the controller walks the row to
    the smallest bucket (observable in k_row_buckets while the row is
    live), and the stream still equals plain greedy decode exactly —
    collapse degrades speculation, never correctness."""
    prompt, n = [7, 3, 11], 60
    with mixed_engine(params, max_batch=2,
                      **spec_kw("draft", draft_params)) as eng:
        r = eng.submit(prompt, n)
        saw_small = False
        deadline = time.monotonic() + 120
        while not r.done.is_set() and time.monotonic() < deadline:
            sp = eng.stats().get("speculative") or {}
            if (sp.get("k_row_buckets") or {}).get("1", 0) >= 1:
                saw_small = True
                break
            time.sleep(0.003)
        np.testing.assert_array_equal(r.wait(timeout=300),
                                      expected(oracle, prompt, n))
        sp = eng.stats()["speculative"]
        assert saw_small, sp
        assert sp["acceptance_rate"] is None or sp["acceptance_rate"] < 0.5
        assert_no_leak(eng)
        assert_spec_idle(eng)


@pytest.mark.slow
@pytest.mark.parametrize("kv_dtype", [None, "int8", "int4"])
@pytest.mark.parametrize("proposer", ["pld", "draft"])
def test_spec_mixed_matches_serialized_property_sweep(params, draft_params,
                                                      kv_dtype, proposer):
    """§22 property sweep (proposer x page dtype x eos-mid-decode):
    concurrent greedy spec streams out of the mixed loop are
    bit-identical to the serialized spec schedule, and every run ends
    with both pools leak-free."""
    prompts = [(list(range(3, 30)), 10), ([9, 8, 7, 6], 8),
               (list(range(50, 85)), 6)]

    def run(eos_id, mixed):
        kw = {"mixed_token_budget": 24} if mixed else {}
        kw.update(spec_kw(proposer, draft_params))
        with ContinuousBatchingEngine(
                CFG, params, max_seq=96, max_batch=4, sampling=GREEDY,
                seed=3, prompt_buckets=(16, 48), kv_block_tokens=8,
                prefill_chunk=8, decode_block=4, eos_id=eos_id,
                kv_dtype=kv_dtype, **kw) as eng:
            reqs = [eng.submit(p, n) for p, n in prompts]
            outs = [list(r.wait(timeout=300)) for r in reqs]
            assert_no_leak(eng)
            assert_spec_idle(eng)
            return outs

    base = run(None, mixed=False)
    assert run(None, mixed=True) == base
    eos = int(base[0][4])
    assert run(eos, mixed=True) == run(eos, mixed=False)
