"""Checkpoint/resume: params round-trip, train-state versioning, retention,
crash-resume, and identity validation (SURVEY.md §5.4: the reference has
none of this)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_inference_demo_tpu.checkpoint import (
    TrainCheckpointManager, load_params, save_params)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params


def _tree_equal(a, b):
    fa, fb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(fa) == len(fb)
    for x, y in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.parametrize("model", ["llama-test", "llama-test-int8",
                                   "llama-test-int4"])
def test_params_roundtrip(tmp_path, model):
    from distributed_inference_demo_tpu.ops.quant import maybe_quantize
    cfg = get_model_config(model)
    params = maybe_quantize(init_full_params(jax.random.PRNGKey(0), cfg),
                            cfg)
    path = str(tmp_path / "ckpt")
    save_params(path, params, cfg, model, metadata={"note": "r1"})
    got, meta = load_params(path, cfg, model_name=model)
    _tree_equal(params, got)
    assert meta["metadata"]["note"] == "r1"


def test_params_identity_validation(tmp_path):
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    path = str(tmp_path / "ckpt")
    save_params(path, params, cfg, "llama-test")
    with pytest.raises(ValueError, match="not 'bloom-test'"):
        load_params(path, get_model_config("bloom-test"),
                    model_name="bloom-test")


def test_train_manager_versioning_and_resume(tmp_path):
    cfg = get_model_config("llama-test")
    opt = optax.adamw(1e-3)
    mgr = TrainCheckpointManager(str(tmp_path / "train"), cfg, opt,
                                 max_to_keep=2)

    # fresh start
    step, params, opt_state = mgr.restore_or_init(seed=0)
    assert step == 0

    # fake three training steps with distinguishable params
    for s in (1, 2, 3):
        params = jax.tree.map(lambda x: x + s if x.dtype != jnp.int32 else x,
                              params)
        mgr.save(s, params, opt_state)
    assert mgr.latest_step == 3
    assert mgr.all_steps() == [2, 3]      # max_to_keep pruned step 1

    # crash-resume: a fresh manager picks up step 3 with identical params
    mgr2 = TrainCheckpointManager(str(tmp_path / "train"), cfg, opt,
                                  max_to_keep=2)
    step2, params2, opt_state2 = mgr2.restore_or_init()
    assert step2 == 3
    _tree_equal(params, params2)
    _tree_equal(opt_state, opt_state2)
    mgr.close()
    mgr2.close()


def test_restore_empty_dir_raises(tmp_path):
    cfg = get_model_config("llama-test")
    mgr = TrainCheckpointManager(str(tmp_path / "none"), cfg,
                                 optax.sgd(1e-2))
    with pytest.raises(FileNotFoundError):
        mgr.restore()
    mgr.close()


def test_load_or_init_accepts_framework_checkpoint(tmp_path):
    """CLI --checkpoint path: load_or_init must recognize our own format."""
    from distributed_inference_demo_tpu.models.loader import load_or_init
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(7), cfg)
    path = str(tmp_path / "ckpt")
    save_params(path, params, cfg, "llama-test")
    got = load_or_init("llama-test", cfg, path)
    _tree_equal(params, got)


def test_train_manager_int8_crash_resume(tmp_path):
    """int8 configs: fresh init must produce the quantized tree so a saved
    state restores against the quantized template (crash-resume parity)."""
    cfg = get_model_config("llama-test-int8")
    opt = optax.sgd(1e-2)
    mgr = TrainCheckpointManager(str(tmp_path / "t8"), cfg, opt)
    step, params, opt_state = mgr.restore_or_init(seed=0)
    mgr.save(1, params, opt_state)
    mgr2 = TrainCheckpointManager(str(tmp_path / "t8"), cfg, opt)
    step2, params2, _ = mgr2.restore_or_init()
    assert step2 == 1
    _tree_equal(params, params2)
    mgr.close()
    mgr2.close()


def test_quantization_mismatch_rejected(tmp_path):
    cfg = get_model_config("llama-test")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    path = str(tmp_path / "ckpt")
    save_params(path, params, cfg, "llama-test")
    with pytest.raises(ValueError, match="quantization"):
        load_params(path, get_model_config("llama-test-int8"))
