"""Model-core tests: decoding correctness properties that the reference
demonstrably lacks (no KV cache — SURVEY.md §2.7) plus stage-split parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_inference_demo_tpu.models import (
    KVCache, get_model_config, StageSpec)
from distributed_inference_demo_tpu.models.base import (
    slice_stage, split_layer_ranges)
from distributed_inference_demo_tpu.models.decoder import (
    init_full_params, stage_forward)
from distributed_inference_demo_tpu.ops.sampling import (
    SamplingParams, sample_logits)


FAMILIES = ["llama-test", "bloom-test", "mixtral-test"]


def _full_spec(cfg):
    return StageSpec(0, 1, 0, cfg.num_layers)


@pytest.mark.parametrize("name", FAMILIES)
@pytest.mark.quick
def test_forward_shapes(name):
    cfg = get_model_config(name)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    spec = _full_spec(cfg)
    ids = jnp.arange(12, dtype=jnp.int32).reshape(2, 6) % cfg.vocab_size
    cache = KVCache.create(cfg, cfg.num_layers, batch=2, max_seq=32)
    pos = jnp.broadcast_to(jnp.arange(6), (2, 6))
    logits, cache2 = stage_forward(params, cfg, spec, ids, cache, pos)
    assert logits.shape == (2, 6, cfg.vocab_size)
    assert int(cache2.length) == 6
    assert np.isfinite(np.asarray(logits, np.float32)).all()


@pytest.mark.parametrize("name", [
    "llama-test", "bloom-test",
    # MoE twin — slow lane: the cache layout is llama's; the routed
    # part is pinned quick by test_expert EP parity + hf_parity decode
    pytest.param("mixtral-test", marks=pytest.mark.slow),
])
def test_kv_cache_decode_matches_full_prefill(name):
    """Prefill(N) then decode 1-by-1 must equal prefill(N+k) logits.

    This is THE property the reference loses by feeding only the last token
    with no cache (Communication.java:322-327)."""
    cfg = get_model_config(name)
    params = init_full_params(jax.random.PRNGKey(1), cfg)
    spec = _full_spec(cfg)
    total = 10
    ids = (jax.random.randint(jax.random.PRNGKey(2), (1, total), 0,
                              cfg.vocab_size)).astype(jnp.int32)

    # one-shot full forward
    cache_a = KVCache.create(cfg, cfg.num_layers, 1, max_seq=32)
    pos = jnp.arange(total)[None, :]
    full_logits, _ = stage_forward(params, cfg, spec, ids, cache_a, pos)

    # prefill 6, then 4 single-token decode steps
    cache_b = KVCache.create(cfg, cfg.num_layers, 1, max_seq=32)
    out, cache_b = stage_forward(params, cfg, spec, ids[:, :6], cache_b,
                                 jnp.arange(6)[None, :])
    step_logits = [out]
    for t in range(6, total):
        out, cache_b = stage_forward(
            params, cfg, spec, ids[:, t:t + 1], cache_b,
            jnp.asarray([[t]], jnp.int32))
        step_logits.append(out)
    stepped = jnp.concatenate(step_logits, axis=1)
    np.testing.assert_allclose(np.asarray(full_logits, np.float32),
                               np.asarray(stepped, np.float32),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("name", FAMILIES)
def test_stage_split_matches_monolithic(name):
    """Running layer ranges across 2 'pipeline stages' must reproduce the
    single-stage logits exactly (the inter-stage seam is lossless)."""
    cfg = get_model_config(name)
    params = init_full_params(jax.random.PRNGKey(3), cfg)
    ids = jnp.arange(8, dtype=jnp.int32).reshape(1, 8) % cfg.vocab_size
    pos = jnp.arange(8)[None, :]

    mono, _ = stage_forward(params, cfg, _full_spec(cfg), ids,
                            KVCache.create(cfg, cfg.num_layers, 1, 32), pos)

    specs = split_layer_ranges(cfg.num_layers, 2)
    x = ids
    for spec in specs:
        sp = slice_stage(params, cfg, spec)
        cache = KVCache.create(cfg, spec.num_layers, 1, 32)
        x, _ = stage_forward(sp, cfg, spec, x, cache, pos)
    np.testing.assert_allclose(np.asarray(mono, np.float32),
                               np.asarray(x, np.float32), rtol=1e-5, atol=1e-5)


def test_split_layer_ranges_weighted():
    specs = split_layer_ranges(10, 3)
    assert sum(s.num_layers for s in specs) == 10
    assert all(s.num_layers >= 3 for s in specs)  # even-ish split
    assert specs[0].layer_start == 0 and specs[-1].layer_end == 10
    # weighted: heavy front layers -> smaller first range
    specs_w = split_layer_ranges(10, 2, weights=[4] * 2 + [1] * 8)
    assert specs_w[0].num_layers < specs_w[1].num_layers
    # heavy tail: the heavy layer must not drag everything into stage 0
    specs_t = split_layer_ranges(5, 2, weights=[1, 1, 1, 1, 100])
    assert all(s.num_layers >= 1 for s in specs_t)
    assert specs_t[1].layer_start == 4  # heavy layer isolated
    # more stages than layers is an error, not empty stages
    with pytest.raises(ValueError):
        split_layer_ranges(3, 5)


def test_int8_quantization():
    """-int8 catalog names produce genuinely quantized weights whose logits
    track the fp ones (reference parity: data/Data.kt int8 variants)."""
    from distributed_inference_demo_tpu.models.loader import load_or_init
    from distributed_inference_demo_tpu.ops.quant import QuantizedArray

    cfg = get_model_config("llama-test")
    cfg_q = cfg.replace(quantization="int8")
    assert get_model_config("bloom560m-int8").quantization == "int8"

    params = load_or_init("llama-test", cfg)
    params_q = load_or_init("llama-test", cfg_q)
    assert isinstance(params_q.layers["wq"], QuantizedArray)
    assert params_q.layers["wq"].q.dtype.name == "int8"
    # int8 stack is ~4x smaller than the f32 test weights
    assert params_q.layers["wq"].nbytes < params.layers["wq"].nbytes / 2

    ids = jnp.arange(6, dtype=jnp.int32)[None, :] % cfg.vocab_size
    pos = jnp.arange(6)[None, :]
    spec = _full_spec(cfg)
    # approximation property: quantizing THE SAME float tree must track its
    # logits.  (The -int8 random-init path above draws per-layer keys — a
    # different weight stream by design, bounded-memory init — so it can't
    # be compared against the float init value for value.)
    from distributed_inference_demo_tpu.ops.quant import maybe_quantize
    params_same_q = maybe_quantize(params, cfg_q)
    lf, _ = stage_forward(params, cfg, spec, ids,
                          KVCache.create(cfg, cfg.num_layers, 1, 32), pos)
    lq, _ = stage_forward(params_same_q, cfg_q, spec, ids,
                          KVCache.create(cfg, cfg.num_layers, 1, 32), pos)
    # quantized logits approximate fp logits (same argmax on most positions)
    agree = (np.argmax(np.asarray(lf), -1) == np.argmax(np.asarray(lq), -1))
    assert agree.mean() >= 0.5
    # and the int8-init path itself must produce finite, usable logits
    li, _ = stage_forward(params_q, cfg_q, spec, ids,
                          KVCache.create(cfg, cfg.num_layers, 1, 32), pos)
    assert np.isfinite(np.asarray(li, np.float32)).all()
    # quantized stage slicing works (QuantizedArray is a pytree)
    sp = slice_stage(params_q, cfg_q, split_layer_ranges(cfg.num_layers, 2)[0])
    assert sp.layers["wq"].q.shape[0] == split_layer_ranges(cfg.num_layers, 2)[0].num_layers


def test_sampling_modes():
    rng = jax.random.PRNGKey(0)
    logits = jnp.asarray([[0.0, 5.0, 1.0, -2.0]] * 4)
    greedy = sample_logits(logits, rng, SamplingParams(greedy=True))
    assert (np.asarray(greedy) == 1).all()
    # top_k=1 == greedy regardless of rng
    topk1 = sample_logits(logits, rng, SamplingParams(top_k=1, temperature=0.9))
    assert (np.asarray(topk1) == 1).all()
    # top_k=2 never samples outside {1, 2}
    for seed in range(5):
        s = sample_logits(logits, jax.random.PRNGKey(seed),
                          SamplingParams(top_k=2, temperature=1.0))
        assert set(np.asarray(s).tolist()) <= {1, 2}
    # top_p tiny -> only the argmax survives
    topp = sample_logits(logits, rng, SamplingParams(top_k=0, top_p=0.1))
    assert (np.asarray(topp) == 1).all()


def test_topk_vals_idx_matches_lax_topk():
    from distributed_inference_demo_tpu.ops.sampling import topk_vals_idx
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 257).astype(np.float32))
    # plant duplicates to exercise the tie rule
    x = x.at[:, 11].set(x[:, 3])
    want_v, want_i = jax.lax.top_k(x, 7)
    got_v, got_i = topk_vals_idx(x, 7)
    np.testing.assert_allclose(np.asarray(got_v), np.asarray(want_v))
    np.testing.assert_array_equal(np.asarray(got_i), np.asarray(want_i))


def test_topk_boundary_ties_exactly_k():
    """Logits tying AT the k-th boundary: both the filter and the fused
    draw must keep exactly k first-occurrence tokens — a value-threshold
    filter would keep the tied extra and diverge from the fused draw's
    distribution (the speculative accept/resample contract)."""
    from distributed_inference_demo_tpu.ops.sampling import filtered_logits
    params = SamplingParams(temperature=1.0, top_k=2)
    logits = jnp.asarray([[5.0, 3.0, 3.0, 1.0]])
    f = np.asarray(filtered_logits(logits, params))[0]
    assert np.isfinite(f).sum() == 2         # exactly k survive
    assert np.isfinite(f[[0, 1]]).all()      # first occurrence of the tie
    for s in range(50):
        tok = int(sample_logits(logits, jax.random.PRNGKey(s), params)[0])
        assert tok in (0, 1)


@pytest.mark.slow
def test_topk_fused_draw_matches_filtered_distribution():
    """The [b, k] candidate draw must follow the SAME distribution as a
    categorical over softmax(filtered_logits) — the contract speculative
    decoding's accept/resample rule depends on.  Compare empirical
    frequencies over many seeds against the exact probabilities."""
    from distributed_inference_demo_tpu.ops.sampling import filtered_logits
    params = SamplingParams(temperature=0.7, top_k=3)
    logits = jnp.asarray([[0.0, 2.0, 1.0, -1.0, 1.5]])
    p_exact = np.asarray(
        jax.nn.softmax(filtered_logits(logits, params), axis=-1))[0]
    draws = np.asarray([
        int(sample_logits(logits, jax.random.PRNGKey(s), params)[0])
        for s in range(4000)])
    freq = np.bincount(draws, minlength=5) / draws.size
    # zero-probability tokens must never appear; kept tokens within 3 sigma
    assert freq[p_exact == 0].sum() == 0
    for tok in np.nonzero(p_exact)[0]:
        sigma = np.sqrt(p_exact[tok] * (1 - p_exact[tok]) / draws.size)
        assert abs(freq[tok] - p_exact[tok]) < 3 * sigma + 1e-9, (
            tok, freq[tok], p_exact[tok])


def test_min_p_filter_and_fused_draw_agree():
    """min-p keeps tokens with prob >= min_p * max_prob on the scaled
    distribution; the full-vocab filter and the fused small-k draw must
    produce the same candidate set."""
    from distributed_inference_demo_tpu.ops.sampling import filtered_logits
    logits = jnp.asarray([[0.0, 5.0, 4.9, 1.0, -3.0]])
    # temp 1.0: threshold = 5 + ln(0.5) ~= 4.31 -> only tokens 1, 2 survive
    params = SamplingParams(temperature=1.0, top_k=0, min_p=0.5)
    f = np.asarray(filtered_logits(logits, params))[0]
    assert np.isfinite(f[[1, 2]]).all()
    assert not np.isfinite(f[[0, 3, 4]]).any()
    # fused small-k path (top_k set): identical candidate set
    pk = SamplingParams(temperature=1.0, top_k=4, min_p=0.5)
    f2 = np.asarray(filtered_logits(logits, pk))[0]
    assert np.isfinite(f2[[1, 2]]).all()
    assert not np.isfinite(f2[[0, 3, 4]]).any()
    for s in range(30):
        tok = int(sample_logits(logits, jax.random.PRNGKey(s), pk)[0])
        assert tok in (1, 2)
    # min_p=1.0 degenerates to argmax-only regardless of rng
    only_max = SamplingParams(temperature=1.0, top_k=0, min_p=1.0)
    for s in range(5):
        assert int(sample_logits(logits, jax.random.PRNGKey(s),
                                 only_max)[0]) == 1


def test_min_p_range_validated():
    with pytest.raises(ValueError, match="min_p"):
        SamplingParams(min_p=1.5)
    with pytest.raises(ValueError, match="min_p"):
        SamplingParams(min_p=-0.1)
