"""Gateway: prefix-aware routing, replica health debounce, proxy retry.

Three layers, cheapest first:

- pure unit tests over the router/registry decision logic (injected
  clock + prober, no sockets);
- HTTP-level tests against STUB replicas (a few dozen lines of
  ThreadingHTTPServer speaking just enough of the serving surface) —
  retry-before-first-token, 503 propagation, mid-stream socket death,
  /metrics + /debugz smoke;
- loopback soak over THREE real continuous-batching replicas, plus the
  mid-stream replica-kill chaos test reusing comm/faults crash rules —
  the greedy-oracle bit-identity contract survives the gateway hop.
"""

import json
import socket
import sys
import threading
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path

import numpy as np
import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax  # noqa: E402

from distributed_inference_demo_tpu.comm.faults import (FaultPlan,
                                                        FaultRule,
                                                        InjectedCrash)
from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime.batching import (
    ContinuousBatchingEngine)
from distributed_inference_demo_tpu.runtime.gateway import (
    GatewayHTTPServer, PrefixAwareRouter, ReplicaRegistry)
from distributed_inference_demo_tpu.runtime.http_server import (
    InferenceHTTPServer)
from distributed_inference_demo_tpu.runtime.overload import GatewayOverloaded

CFG = get_model_config("llama-test")
GREEDY = SamplingParams(greedy=True)


@pytest.fixture(scope="module")
def params():
    return init_full_params(jax.random.PRNGKey(0), CFG)


# ---------------------------------------------------------------------------
# unit: router + registry decision logic (no sockets)
# ---------------------------------------------------------------------------

class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _registry(n=3, **kw):
    kw.setdefault("prober", lambda h, p: {"queue_depth": 0})
    return ReplicaRegistry([("10.0.0.1", 7000 + i) for i in range(n)],
                           **kw)


@pytest.mark.quick
def test_prefix_route_follows_history_and_falls_back_to_hash():
    router = PrefixAwareRouter(_registry(), min_prefix_tokens=8,
                               block_tokens=8)
    toks = list(range(2, 34))
    d0 = router.route(toks)
    assert d0.policy == "hash" and d0.match_tokens == 0
    # two alternates ride along for retry, in rendezvous order
    assert len(d0.candidates) == 2 and d0.rid not in d0.candidates
    router.record(d0.rid, toks)
    d1 = router.route(toks)
    assert d1.policy == "prefix" and d1.rid == d0.rid
    assert d1.match_tokens == 32
    # a prompt sharing only one block still follows (8 >= min_prefix)
    d2 = router.route(toks[:8] + [999] * 24)
    assert d2.policy == "prefix" and d2.rid == d0.rid
    assert d2.match_tokens == 8
    # an unrelated prompt hashes
    assert router.route([500 + i for i in range(32)]).policy == "hash"


@pytest.mark.quick
def test_short_match_stays_on_hash_fallback():
    router = PrefixAwareRouter(_registry(), min_prefix_tokens=16,
                               block_tokens=8)
    toks = list(range(2, 34))
    d0 = router.route(toks)
    router.record(d0.rid, toks)
    # only one 8-token block matches: below min_prefix_tokens=16
    d = router.route(toks[:8] + [999] * 24)
    assert d.policy == "hash" and d.match_tokens == 0


@pytest.mark.quick
def test_rendezvous_hash_is_deterministic_and_stable_under_eviction():
    reg = _registry(3, sustain=1)
    router = PrefixAwareRouter(reg, min_prefix_tokens=64, block_tokens=8)
    toks = list(range(2, 34))
    d1, d2 = router.route(toks), router.route(toks)
    assert d1.rid == d2.rid and d1.policy == d2.policy == "hash"
    # rendezvous property: evicting a NON-chosen replica moves nothing
    reg.record_failure(d1.candidates[-1])
    assert not reg.is_up(d1.candidates[-1])
    d3 = router.route(toks)
    assert d3.rid == d1.rid


@pytest.mark.quick
def test_bounded_load_skips_the_hot_hashed_pick():
    router = PrefixAwareRouter(_registry(), min_prefix_tokens=64,
                               block_tokens=8, load_factor=2.0)
    toks = list(range(2, 34))
    d = router.route(toks)
    for _ in range(12):           # load 12 > 2.0 * (1 + mean 4) = 10
        router.acquire(d.rid)
    d2 = router.route(toks)
    assert d2.rid != d.rid
    assert d2.rid == d.candidates[0]   # next in rendezvous order
    for _ in range(12):
        router.release(d.rid)
    assert router.route(toks).rid == d.rid


@pytest.mark.quick
def test_bounded_load_weighs_prefill_backlog_decision_table():
    """ISSUE-15 satellite: the bounded-load walk counts a replica's
    reported prefill backlog (``pending_prefill_tokens`` scaled by
    ``prefill_token_weight``) as queued work — a deep prompt backlog at
    ZERO queue depth sheds hashed traffic exactly like a deep queue,
    weight=0 restores the depth-only behavior, and a uniform backlog
    raises the mean with the load so it causes no churn."""
    toks = list(range(2, 34))

    def scenario(weight, depths, backlogs):
        reg = _registry()
        router = PrefixAwareRouter(reg, min_prefix_tokens=64,
                                   block_tokens=8, load_factor=1.0,
                                   prefill_token_weight=weight)
        d0 = router.route(toks)
        order = [d0.rid] + d0.candidates     # rendezvous order for toks
        for rid, dep, back in zip(order, depths, backlogs):
            reg.record_success(rid, {"queue_depth": dep,
                                     "pending_prefill_tokens": back})
        return order, router.route(toks).rid, router

    # nothing reported: the rendezvous-first replica serves
    order, got, _ = scenario(256, (0, 0, 0), (0, 0, 0))
    assert got == order[0]

    # deep backlog at zero depth sheds the pick: 4096/256 = 16
    # request-equivalents > bound 1.0 * (1 + 16/3)
    order, got, router = scenario(256, (0, 0, 0), (4096, 0, 0))
    assert got == order[1]
    assert router._load(order[0]) == 16.0

    # the same backlog with weight=0 is invisible (depth-only load)
    order, got, _ = scenario(0, (0, 0, 0), (4096, 0, 0))
    assert got == order[0]

    # uniform backlog raises the mean with the load: no churn
    order, got, _ = scenario(256, (0, 0, 0), (4096, 4096, 4096))
    assert got == order[0]

    # depth and backlog ADD: 2 + 1024/256 = 6 > bound 1.0 * (1 + 8/3);
    # the walk settles on the next replica (load 1)
    order, got, router = scenario(256, (2, 1, 1), (1024, 0, 0))
    assert got == order[1]

    # both knobs and the per-replica backlog surface on /debugz
    tab = router.routing_table()
    assert tab["prefill_token_weight"] == 256
    assert tab["replicas"][order[0]]["pending_prefill_tokens"] == 1024


@pytest.mark.quick
def test_bounded_load_weighs_spec_backlog_decision_table():
    """ISSUE-19 satellite: the bounded-load walk folds a replica's
    reported speculative backlog (``spec_backlog_tokens``, the active
    rows' Σ (K_row+1)·decode_block per-iteration spend, scaled by
    ``spec_token_weight``) into the same load it weighs prefill backlog
    with — a replica mid-speculation sheds hashed traffic, weight=0
    ignores it, uniform spec load causes no churn, and spec + prefill
    backlogs ADD."""
    toks = list(range(2, 34))

    def scenario(weight, depths, specs, prefills=(0, 0, 0)):
        reg = _registry()
        router = PrefixAwareRouter(reg, min_prefix_tokens=64,
                                   block_tokens=8, load_factor=1.0,
                                   prefill_token_weight=256,
                                   spec_token_weight=weight)
        d0 = router.route(toks)
        order = [d0.rid] + d0.candidates     # rendezvous order for toks
        for rid, dep, sp, pf in zip(order, depths, specs, prefills):
            reg.record_success(rid, {"queue_depth": dep,
                                     "spec_backlog_tokens": sp,
                                     "pending_prefill_tokens": pf})
        return order, router.route(toks).rid, router

    # nothing reported: rendezvous-first serves
    order, got, _ = scenario(256, (0, 0, 0), (0, 0, 0))
    assert got == order[0]

    # deep spec backlog at zero depth sheds the pick: 4096/256 = 16
    # request-equivalents > bound 1.0 * (1 + 16/3)
    order, got, router = scenario(256, (0, 0, 0), (4096, 0, 0))
    assert got == order[1]
    assert router._load(order[0]) == 16.0

    # the same backlog with weight=0 is invisible
    order, got, _ = scenario(0, (0, 0, 0), (4096, 0, 0))
    assert got == order[0]

    # uniform spec backlog raises the mean with the load: no churn
    order, got, _ = scenario(256, (0, 0, 0), (4096, 4096, 4096))
    assert got == order[0]

    # spec and prefill backlogs ADD: 512/256 + 1024/256 = 6 request-
    # equivalents > bound 1.0 * (1 + 2); the walk moves on
    order, got, router = scenario(256, (0, 0, 0), (512, 0, 0),
                                  (1024, 0, 0))
    assert got == order[1]
    assert router._load(order[0]) == 6.0

    # knob + per-replica gauge surface on /debugz
    tab = router.routing_table()
    assert tab["spec_token_weight"] == 256
    assert tab["replicas"][order[0]]["spec_backlog_tokens"] == 512


@pytest.mark.quick
def test_prefix_tie_breaks_toward_the_lighter_replica():
    reg = _registry()
    router = PrefixAwareRouter(reg, min_prefix_tokens=8, block_tokens=8)
    toks = list(range(2, 34))
    rids = reg.replica_ids()
    router.record(rids[0], toks)
    router.record(rids[1], toks)
    router.acquire(rids[0])
    d = router.route(toks)
    assert d.policy == "prefix" and d.rid == rids[1]


@pytest.mark.quick
def test_host_tier_second_chance_decision_table():
    """The §21 tier-aware route, as a decision table:

    1. device-tier miss everywhere + no tier digests -> hash fallback;
    2. device-tier miss + replica B's REPORTED host tier holds the
       prefix -> route to B with policy host_tier (NOT the rendezvous
       pick);
    3. device-tier history, once learned, wins over the tier hint;
    4. a match below min_prefix_tokens never second-chances;
    5. an empty digest report (tier drained/closed) withdraws B.
    """
    from distributed_inference_demo_tpu.runtime.kvcache.tiered import (
        chain_digests)
    reg = _registry(3)
    router = PrefixAwareRouter(reg, min_prefix_tokens=16, block_tokens=8)
    rids = reg.replica_ids()
    b = rids[1]
    toks = list(range(200, 232))                 # 4 blocks of 8
    keys = [tuple(toks[i * 8:(i + 1) * 8]) for i in range(4)]
    digests = [d.hex()[:16] for d in chain_digests(keys)]

    # row 1: nothing anywhere -> hash
    assert router.route(toks).policy == "hash"

    # row 2: B reports the prefix demoted (the /stats fragment the
    # registry prober carries) -> second chance routes to B
    router.reconcile(b, {"kvcache": {
        "tier": {"block_tokens": 8, "digest": digests}}})
    d = router.route(toks)
    assert d.policy == "host_tier"
    assert d.rid == b
    assert d.match_tokens == 32
    assert router.routing_table()["replicas"][b]["tier_digest_entries"] == 4

    # row 3: once replica A holds it in its DEVICE tree (gateway
    # history), the prefix policy outranks the tier hint
    a = rids[0]
    router.record(a, toks)
    d = router.route(toks)
    assert d.policy == "prefix" and d.rid == a

    # row 4: a one-block tier match (8 < min_prefix_tokens 16) is not
    # good enough — hash, not host_tier
    short = list(range(500, 516))
    short_digest = [chain_digests([tuple(short[:8])])[0].hex()[:16]]
    router.reconcile(b, {"kvcache": {
        "tier": {"block_tokens": 8, "digest": short_digest}}})
    assert router.route(short).policy == "hash"

    # row 5: an empty report withdraws the replica from second chances
    router.reconcile(b, {"kvcache": {
        "tier": {"block_tokens": 8, "digest": []}}})
    other = list(range(600, 632))
    router.reconcile(b, {"kvcache": {"tier": {"block_tokens": 8,
                                              "digest": []}}})
    assert router.route(other).policy == "hash"
    assert router.routing_table()["replicas"][b]["tier_digest_entries"] == 0


@pytest.mark.quick
def test_host_tier_flush_on_readmission_drops_digests():
    reg = _registry(2)
    router = PrefixAwareRouter(reg, min_prefix_tokens=8, block_tokens=8)
    from distributed_inference_demo_tpu.runtime.kvcache.tiered import (
        chain_digests)
    rid = reg.replica_ids()[0]
    toks = list(range(2, 18))
    dgs = [d.hex()[:16] for d in chain_digests(
        [tuple(toks[:8]), tuple(toks[8:])])]
    router.reconcile(rid, {"kvcache": {
        "tier": {"block_tokens": 8, "digest": dgs}}})
    assert router.tier_match_tokens(rid, toks) == 16
    # readmission flush: the replica restarted — its host ring is gone
    router.flush_replica(rid)
    assert router.tier_match_tokens(rid, toks) == 0


@pytest.mark.quick
def test_lru_trim_keeps_the_most_specific_prefix_keys():
    router = PrefixAwareRouter(_registry(), min_prefix_tokens=4,
                               block_tokens=4, max_index_entries=2)
    rid = router.registry.replica_ids()[0]
    toks = list(range(2, 18))     # 16 tokens -> 4 block keys, cap 2
    router.record(rid, toks)
    assert router.match_tokens(rid, toks) == 16
    # the short keys were the ones trimmed: an 8-token prefix misses
    assert router.match_tokens(rid, toks[:8]) == 0


@pytest.mark.quick
def test_eviction_readmission_debounce_with_injected_clock():
    clk = _Clock()
    reg = _registry(2, sustain=3, readmit_cooldown_s=5.0, clock=clk)
    router = PrefixAwareRouter(reg, min_prefix_tokens=8, block_tokens=8)
    rid = reg.replica_ids()[0]
    toks = list(range(2, 18))
    router.record(rid, toks)
    # two strikes: a blip, not an outage
    reg.record_failure(rid)
    reg.record_failure(rid)
    assert reg.is_up(rid)
    # a success wipes the streak entirely
    reg.record_success(rid)
    reg.record_failure(rid)
    reg.record_failure(rid)
    assert reg.is_up(rid)
    # the sustained third strike evicts
    reg.record_failure(rid)
    assert not reg.is_up(rid)
    assert rid not in reg.up_replicas()
    # a success INSIDE the cooldown clears the streak but does not
    # readmit — a flapping process must prove a quiet period
    clk.t += 2.0
    reg.record_success(rid, {"queue_depth": 0})
    assert not reg.is_up(rid)
    # past the cooldown a success readmits, and the router's history
    # for the replica is flushed (its cache state is unknown)
    clk.t += 4.0
    reg.record_success(rid, {"queue_depth": 0})
    assert reg.is_up(rid)
    assert router.match_tokens(rid, toks) == 0


@pytest.mark.quick
def test_probe_and_proxy_failures_share_one_streak():
    boom = RuntimeError("connection refused")

    def prober(host, port):
        raise boom

    reg = _registry(2, sustain=3, prober=prober)
    rid = reg.replica_ids()[0]
    reg.probe_all()                  # one strike per replica
    reg.record_failure(rid, reason="proxy: reset")   # strike 2
    assert reg.is_up(rid)
    reg.probe_all()                  # strike 3 evicts rid (and peer hits 2)
    assert not reg.is_up(rid)
    assert reg.is_up(reg.replica_ids()[1])


@pytest.mark.quick
def test_reconcile_flushes_history_when_replica_tree_resets():
    reg = _registry()
    router = PrefixAwareRouter(reg, min_prefix_tokens=8, block_tokens=8)
    rid = reg.replica_ids()[0]
    toks = list(range(2, 18))
    router.reconcile(rid, {"kvcache": {"nodes": 3}})
    router.record(rid, toks)
    assert router.match_tokens(rid, toks) == 16
    # same occupancy: nothing happens
    router.reconcile(rid, {"kvcache": {"nodes": 3}})
    assert router.match_tokens(rid, toks) == 16
    # the replica's tree emptied (restart / eviction storm): flush
    router.reconcile(rid, {"kvcache": {"nodes": 0}})
    assert router.match_tokens(rid, toks) == 0


@pytest.mark.quick
def test_route_raises_gateway_overloaded_when_all_replicas_down():
    reg = _registry(2, sustain=1)
    router = PrefixAwareRouter(reg, min_prefix_tokens=8, block_tokens=8)
    for rid in reg.replica_ids():
        reg.record_failure(rid)
    with pytest.raises(GatewayOverloaded):
        router.route(list(range(2, 18)))


@pytest.mark.quick
def test_draining_replica_stops_routing_without_a_strike():
    """The §18 drain satellite: a draining replica leaves
    routable_replicas (no NEW request routes to it) while staying UP —
    no eviction strike, health debounce untouched — and undraining
    restores it."""
    reg = _registry()
    router = PrefixAwareRouter(reg, min_prefix_tokens=8, block_tokens=8)
    victim = reg.replica_ids()[0]
    reg.set_draining(victim)
    assert reg.is_draining(victim)
    assert reg.is_up(victim)                 # health is orthogonal
    assert reg.get(victim).fail_streak == 0  # drain is NOT a strike
    assert victim in reg.up_replicas()
    assert victim not in reg.routable_replicas()
    # the router never picks it, prefix history or not
    router.record(victim, list(range(2, 34)))
    for salt in range(12):
        d = router.route(list(range(2, 34)) + [salt])
        assert d.rid != victim and victim not in d.candidates
    # surfaced on the debug planes
    assert reg.debug_state()["replicas"][victim]["draining"] is True
    assert router.routing_table()["replicas"][victim]["draining"] is True
    # idempotent set + undrain restores routing
    reg.set_draining(victim)
    reg.set_draining(victim, False)
    assert victim in reg.routable_replicas()
    assert not reg.is_draining(victim)


@pytest.mark.quick
def test_every_replica_draining_sheds_like_all_down():
    reg = _registry(2)
    router = PrefixAwareRouter(reg, min_prefix_tokens=8, block_tokens=8)
    for rid in reg.replica_ids():
        reg.set_draining(rid)
    with pytest.raises(GatewayOverloaded, match="draining"):
        router.route(list(range(2, 18)))


# ---------------------------------------------------------------------------
# HTTP-level: stub replicas (no engine, no jax compute)
# ---------------------------------------------------------------------------

class _StubReplica:
    """A replica double speaking just enough of the serving surface:
    ``GET /stats`` for the prober and a chunked-JSONL ``POST
    /generate``.  ``shed`` makes it answer 503/429 + Retry-After;
    ``sever_after`` kills the SOCKET after N stream lines (no
    terminating chunk) — the mid-stream death the gateway must turn
    into an error line, never a hang."""

    def __init__(self, lines=3, shed=None, sever_after=None):
        self.lines = lines
        self.shed = shed
        self.sever_after = sever_after
        self.requests = 0
        self.trace_ids = []
        outer = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({"queue_depth": 0,
                                   "kvcache": {"nodes": 1}}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                outer.requests += 1
                tid = self.headers.get("X-DWT-Trace-Id")
                if tid:
                    outer.trace_ids.append(tid)
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                if outer.shed is not None:
                    body = json.dumps({"error": "replica saturated"}
                                      ).encode()
                    self.send_response(outer.shed)
                    self.send_header("Retry-After", "7")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/jsonl")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(data):
                    self.wfile.write(f"{len(data):x}\r\n".encode())
                    self.wfile.write(data + b"\r\n")

                for i in range(outer.lines):
                    if (outer.sever_after is not None
                            and i >= outer.sever_after):
                        self.wfile.flush()
                        # a real FIN, not just a dropped handle (the
                        # handler's buffered files keep the fd alive):
                        # the peer sees EOF with NO terminating chunk
                        self.close_connection = True
                        self.connection.shutdown(socket.SHUT_RDWR)
                        return
                    chunk(json.dumps({"step": i, "tokens": [100 + i]}
                                     ).encode() + b"\n")
                chunk(b"")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.host, self.port = self.httpd.server_address
        self.rid = f"{self.host}:{self.port}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _dead_endpoint():
    """A (host, port) nothing listens on — connects are refused fast."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return "127.0.0.1", port


def _post_stream(host, port, body, timeout=60):
    """POST /generate with stream=True; returns (status, headers,
    parsed JSONL lines, truncated_flag)."""
    conn = HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/generate", body=json.dumps(body),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        headers = dict(resp.getheaders())
        if resp.status != 200:
            return resp.status, headers, [json.loads(resp.read())], False
        lines, truncated = [], False
        try:
            while True:
                ln = resp.readline()
                if not ln:
                    break
                ln = ln.strip()
                if ln:
                    lines.append(json.loads(ln))
        except Exception:
            truncated = True
        return resp.status, headers, lines, truncated
    finally:
        conn.close()


def _gateway(replicas, *, retry_limit=1, resume_limit=1, sustain=3,
             min_prefix=8, block_tokens=8, start_prober=False,
             cooldown=60.0):
    registry = ReplicaRegistry(replicas, sustain=sustain,
                               readmit_cooldown_s=cooldown,
                               probe_interval_s=0.2)
    router = PrefixAwareRouter(registry, min_prefix_tokens=min_prefix,
                               block_tokens=block_tokens)
    gw = GatewayHTTPServer(registry, router, port=0,
                           retry_limit=retry_limit,
                           resume_limit=resume_limit)
    if start_prober:
        gw.start()
    else:
        # http thread only: tests drive the debounce deterministically
        threading.Thread(target=gw.httpd.serve_forever,
                         daemon=True).start()
    return gw


@pytest.mark.quick
def test_retry_before_first_token_on_a_dead_replica():
    stub = _StubReplica(lines=3)
    dead = _dead_endpoint()
    gw = _gateway([dead, (stub.host, stub.port)])
    try:
        toks = list(range(2, 18))
        # teach the router the DEAD replica holds this prefix
        gw.router.record(f"{dead[0]}:{dead[1]}", toks)
        st, headers, lines, truncated = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 3, "stream": True})
        assert st == 200 and not truncated
        assert [d["tokens"][0] for d in lines] == [100, 101, 102]
        # the retry landed on the live stub, and the client can see it
        assert headers["X-DWT-Replica"] == stub.rid
        assert stub.requests == 1
        # the dead replica took a strike on the shared streak
        assert gw.registry.get(f"{dead[0]}:{dead[1]}").fail_streak >= 1
    finally:
        gw.shutdown()
        stub.close()


@pytest.mark.quick
def test_replica_shed_propagates_with_retry_after_and_no_retry():
    shedding = _StubReplica(shed=503)
    healthy = _StubReplica(lines=2)
    gw = _gateway([(shedding.host, shedding.port),
                   (healthy.host, healthy.port)])
    try:
        toks = list(range(2, 18))
        gw.router.record(shedding.rid, toks)
        st, headers, lines, _ = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 3, "stream": True})
        # federated admission: the replica's own 503 is the answer —
        # Retry-After propagates verbatim, no second replica is tried
        assert st == 503
        assert headers["Retry-After"] == "7"
        assert "saturated" in lines[0]["error"]
        assert healthy.requests == 0
    finally:
        gw.shutdown()
        shedding.close()
        healthy.close()


@pytest.mark.quick
def test_gateway_sheds_503_when_every_candidate_is_dead():
    gw = _gateway([_dead_endpoint(), _dead_endpoint()], retry_limit=2)
    try:
        st, headers, lines, _ = _post_stream(
            gw.host, gw.port, {"prompt_ids": [list(range(2, 18))],
                               "max_new_tokens": 3, "stream": True})
        assert st == 503
        assert "Retry-After" in headers
        assert "every candidate replica" in lines[0]["error"]
    finally:
        gw.shutdown()


@pytest.mark.quick
def test_midstream_socket_death_becomes_error_line_not_a_hang():
    severing = _StubReplica(lines=5, sever_after=2)
    gw = _gateway([(severing.host, severing.port)], sustain=1)
    try:
        st, _, lines, _ = _post_stream(
            gw.host, gw.port, {"prompt_ids": [list(range(2, 18))],
                               "max_new_tokens": 5, "stream": True},
            timeout=30)
        # first token was forwarded, so no retry: the delivered prefix
        # plus ONE error line, framing intact, stream terminated
        assert st == 200
        assert [d["tokens"][0] for d in lines[:2]] == [100, 101]
        assert "error" in lines[-1]
        assert severing.rid in lines[-1]["error"]
        # the mid-stream death struck the replica out of routing
        assert not gw.registry.is_up(severing.rid)
    finally:
        gw.shutdown()
        severing.close()


@pytest.mark.quick
def test_gateway_metrics_debugz_and_trace_surfaces():
    stub = _StubReplica(lines=2)
    gw = _gateway([(stub.host, stub.port)], start_prober=True)
    try:
        toks = list(range(2, 18))
        for _ in range(2):
            st, _, _, _ = _post_stream(
                gw.host, gw.port, {"prompt_ids": [toks],
                                   "max_new_tokens": 2, "stream": True})
            assert st == 200
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        conn.request("GET", "/metrics")
        text = conn.getresponse().read().decode()
        conn.close()
        for name in ("dwt_gateway_prefix_routed_requests_total",
                     "dwt_gateway_hashed_requests_total",
                     "dwt_gateway_retried_requests_total",
                     "dwt_gateway_shed_requests_total",
                     "dwt_gateway_replica_down_total",
                     "dwt_gateway_replica_up_total",
                     "dwt_gateway_up_replicas",
                     "dwt_gateway_proxy_ttft_seconds"):
            assert name in text, name
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        conn.request("GET", "/debugz")
        dz = json.loads(conn.getresponse().read())
        conn.close()
        assert stub.rid in dz["routing"]["replicas"]
        row = dz["routing"]["replicas"][stub.rid]
        assert row["routed"] == 2 and row["up"] is True
        assert row["index_entries"] >= 1
        assert dz["registry"]["replicas"][stub.rid]["fail_streak"] == 0
        # one trace id covered gateway -> replica: the replica saw the
        # header, and the gateway's /trace holds route + proxy spans
        assert len(stub.trace_ids) == 2
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        conn.request("GET", "/trace")
        tr = json.loads(conn.getresponse().read())
        conn.close()
        names = {ev["name"] for ev in tr["traceEvents"]}
        assert {"gateway.route", "gateway.proxy"} <= names
    finally:
        gw.shutdown()
        stub.close()


@pytest.mark.quick
def test_drain_endpoint_flips_routing_and_keeps_proxying(params=None):
    """POST /drain: the drained stub stops receiving NEW requests (they
    all land on the other replica) while /health degrades gracefully
    and /debugz names the drained replica; undrain restores it."""
    stubs = [_StubReplica(lines=2), _StubReplica(lines=2)]
    gw = _gateway([(s.host, s.port) for s in stubs], min_prefix=8,
                  block_tokens=8)
    try:
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        conn.request("POST", "/drain", body=json.dumps(
            {"replica": stubs[0].rid}))
        resp = conn.getresponse()
        out = json.loads(resp.read())
        conn.close()
        assert resp.status == 200
        assert out["draining"] is True
        assert out["routable"] == [stubs[1].rid]
        # unknown replica: 400, names the fleet
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        conn.request("POST", "/drain", body=json.dumps(
            {"replica": "nope:1"}))
        resp = conn.getresponse()
        assert resp.status == 400
        assert "replicas" in json.loads(resp.read())
        conn.close()
        # every generate lands on the OTHER stub
        before = stubs[0].requests
        for i in range(6):
            st, headers, _, _ = _post_stream(
                gw.host, gw.port,
                {"prompt_ids": [list(range(2, 18)) + [i]],
                 "max_new_tokens": 2, "stream": True})
            assert st == 200
            assert headers["X-DWT-Replica"] == stubs[1].rid
        assert stubs[0].requests == before
        # surfaced: /health stays ok (one routable), /debugz names it
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        conn.request("GET", "/health")
        health = json.loads(conn.getresponse().read())
        conn.close()
        assert health["status"] == "ok"
        assert health["replicas_routable"] == 1
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        conn.request("GET", "/debugz")
        dbg = json.loads(conn.getresponse().read())
        conn.close()
        assert dbg["registry"]["replicas"][stubs[0].rid]["draining"]
        # undrain restores routing
        conn = HTTPConnection(gw.host, gw.port, timeout=10)
        conn.request("POST", "/drain", body=json.dumps(
            {"replica": stubs[0].rid, "draining": False}))
        resp = conn.getresponse()
        assert json.loads(resp.read())["draining"] is False
        conn.close()
        assert set(gw.registry.routable_replicas()) == {
            stubs[0].rid, stubs[1].rid}
    finally:
        gw.shutdown()
        for s in stubs:
            s.close()


# ---------------------------------------------------------------------------
# loopback soak: real replicas, real engines
# ---------------------------------------------------------------------------

def _engine(params, **kw):
    kw.setdefault("max_seq", 96)
    kw.setdefault("max_batch", 2)
    kw.setdefault("sampling", GREEDY)
    kw.setdefault("kv_cache_blocks", 0)
    kw.setdefault("kv_block_tokens", 8)
    return ContinuousBatchingEngine(CFG, params, **kw)


# tier-1 budget: the routing decision tables + proxy tests keep the
# quick-lane reps; the three-replica soak rides the slow lane
@pytest.mark.slow
def test_loopback_soak_three_replicas_cache_aware(params):
    """The -m quick representative of the gateway soak: three real
    replicas, grouped shared-prefix workload, every answer bit-identical
    to the replica's own direct answer, groups sticking to one replica
    after the first member."""
    engines = [_engine(params) for _ in range(3)]
    servers = []
    for eng in engines:
        srv = InferenceHTTPServer(eng, port=0)
        srv.start()
        servers.append(srv)
    gw = _gateway([(s.host, s.port) for s in servers], min_prefix=8,
                  block_tokens=8, start_prober=True)
    try:
        rng = np.random.default_rng(3)
        groups = [list(rng.integers(2, CFG.vocab_size - 1, 16))
                  for _ in range(2)]
        served = {}       # group index -> replica rid
        outputs = {}
        for round_i in range(3):
            for g, prefix in enumerate(groups):
                toks = [int(t) for t in prefix] + [2 + g, 3 + round_i]
                st, headers, lines, truncated = _post_stream(
                    gw.host, gw.port,
                    {"prompt_ids": [toks], "max_new_tokens": 4,
                     "stream": True}, timeout=300)
                assert st == 200 and not truncated
                rid = headers["X-DWT-Replica"]
                served.setdefault(g, rid)
                # after the first member, the group STICKS
                assert rid == served[g], (g, round_i)
                outputs[tuple(toks)] = [d["tokens"][0] for d in lines]
        # bit-identity through the gateway hop: re-ask the replica
        # directly for one prompt per group
        for g, prefix in enumerate(groups):
            toks = [int(t) for t in prefix] + [2 + g, 3]
            host, port = served[g].split(":")
            st, _, lines, _ = _post_stream(
                host, int(port), {"prompt_ids": [toks],
                                  "max_new_tokens": 4, "stream": True},
                timeout=300)
            assert st == 200
            assert [d["tokens"][0] for d in lines] == outputs[tuple(toks)]
        # the routing split is observable: first member hashed, the
        # rest prefix-routed
        table = gw.router.routing_table()["replicas"]
        assert sum(r["prefix_routed"] for r in table.values()) >= 4
        # replica-side evidence: warm prefixes were actually reused
        reused = sum(e.stats()["kvcache"]["partial_hit_tokens"]
                     for e in engines)
        assert reused > 0
    finally:
        gw.shutdown()
        for srv, eng in zip(servers, engines):
            srv.shutdown()
            eng.close()


class _CrashyBackend:
    """Wrap an engine so its token stream consults a comm/faults
    FaultPlan: the crash_after rule raises InjectedCrash mid-stream,
    modeling a replica process dying between decode steps."""

    def __init__(self, inner, plan, rid):
        self._inner = inner
        self._plan = plan
        self._rid = rid

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def generate_stream(self, *a, **kw):
        for item in self._inner.generate_stream(*a, **kw):
            ev = self._plan.on_recv(self._rid)
            if ev is not None:
                raise InjectedCrash(
                    f"{self._rid}: injected crash_after (seq "
                    f"{ev.get('seq')})")
            yield item


def test_midstream_replica_kill_chaos_injected_crash(params):
    """A replica dies mid-stream via a seeded comm/faults crash rule
    with resume DISABLED (--resume-limit 0): the client holds the
    delivered prefix plus an error line (never a hang, never divergent
    tokens), and a follow-up request completes the same greedy answer
    in full on the fleet.  This pins the documented post-resume
    fallback contract; the resume path itself is pinned in
    test_stream_failover.py."""
    plan = FaultPlan(seed=7, rules=[FaultRule(kind="crash_after",
                                              n_msgs=3, max_count=1)])
    engines = [_engine(params) for _ in range(2)]
    servers = []
    for i, eng in enumerate(engines):
        backend = (_CrashyBackend(eng, plan, "replica0") if i == 0
                   else eng)
        srv = InferenceHTTPServer(backend, port=0)
        srv.start()
        servers.append(srv)
    gw = _gateway([(s.host, s.port) for s in servers], min_prefix=8,
                  block_tokens=8, resume_limit=0)
    try:
        toks = list(range(2, 18))
        crashy_rid = f"{servers[0].host}:{servers[0].port}"
        gw.router.record(crashy_rid, toks)
        st, _, lines, _ = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 8, "stream": True},
            timeout=300)
        # the crash fired after 3 streamed steps: delivered prefix +
        # the replica's own error line, forwarded with framing intact
        assert st == 200
        assert "error" in lines[-1] and "injected" in lines[-1]["error"]
        delivered = [d["tokens"][0] for d in lines[:-1]]
        assert len(delivered) == 3
        assert [e["kind"] for e in plan.events] == ["crash_after"]
        # the fleet still answers, and the full greedy stream extends
        # exactly the delivered prefix (bit-identity across the kill)
        st, _, lines, truncated = _post_stream(
            gw.host, gw.port, {"prompt_ids": [toks],
                               "max_new_tokens": 8, "stream": True},
            timeout=300)
        assert st == 200 and not truncated
        full = [d["tokens"][0] for d in lines]
        assert len(full) == 8
        assert full[:3] == delivered
    finally:
        gw.shutdown()
        for srv, eng in zip(servers, engines):
            srv.shutdown()
            eng.close()


@pytest.mark.quick
def test_replica_echoes_trace_header_on_generate(params):
    """The http_server seam: a proxied /generate carries
    X-DWT-Trace-Id, and the replica echoes it on blocking AND
    streaming responses (one trace id covers gateway -> replica)."""
    eng = _engine(params)
    srv = InferenceHTTPServer(eng, port=0)
    srv.start()
    try:
        for stream in (False, True):
            conn = HTTPConnection(srv.host, srv.port, timeout=300)
            conn.request("POST", "/generate", body=json.dumps(
                {"prompt_ids": [list(range(2, 10))],
                 "max_new_tokens": 2, "stream": stream}),
                headers={"Content-Type": "application/json",
                         "X-DWT-Trace-Id": "00ab00ab00ab00ab"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("X-DWT-Trace-Id") == "00ab00ab00ab00ab"
            resp.read()
            conn.close()
    finally:
        srv.shutdown()
        eng.close()
