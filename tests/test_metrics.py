"""Prometheus /metrics endpoint: text exposition validity, counter
monotonicity across generate calls, histogram bucket sanity (ISSUE 1
satellite).  The registry/classes themselves are also unit-covered here
(the handlers are plumbing; the format rules live in telemetry/metrics).
"""

import json
import re
import urllib.request

import numpy as np
import pytest

import jax

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.runtime.http_server import (
    InferenceHTTPServer)
from distributed_inference_demo_tpu.telemetry.metrics import (
    Counter, Gauge, Histogram, MetricError, Registry)

MODEL = "llama-test"
PROMPT = [[5, 17, 42, 7]]

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^}]*\})?\s+(?P<value>\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text):
    """Parse Prometheus text format line by line; assert structural
    validity (HELP/TYPE before samples, parseable sample lines).
    Returns ({(name, frozen_labels): value}, {family: type})."""
    samples, types, helped = {}, {}, set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            helped.add(line.split()[2])
            continue
        if line.startswith("# TYPE "):
            _, _, fam, typ = line.split(None, 3)
            assert typ in ("counter", "gauge", "histogram"), line
            assert fam in helped, f"TYPE before HELP: {line}"
            types[fam] = typ
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        labels = frozenset(_LABEL_RE.findall(m.group("labels") or ""))
        v = m.group("value")
        value = float("inf") if v == "+Inf" else float(v)
        key = (m.group("name"), labels)
        assert key not in samples, f"duplicate sample: {line!r}"
        samples[key] = value
        base = m.group("name")
        for suffix in ("_bucket", "_count", "_sum"):
            if base.endswith(suffix) and base[:-len(suffix)] in types:
                base = base[:-len(suffix)]
        assert base in types, f"sample without TYPE: {line!r}"
    return samples, types


def _get(url):
    with urllib.request.urlopen(url, timeout=60) as r:
        ctype = r.headers.get("Content-Type", "")
        return r.read().decode("utf-8"), ctype


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        return json.loads(r.read())


@pytest.fixture(scope="module")
def served_engine():
    cfg = get_model_config(MODEL)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    engine = InferenceEngine(cfg, params, max_seq=64,
                             sampling=SamplingParams(greedy=True))
    server = InferenceHTTPServer(engine, port=0, model_name=MODEL)
    server.start()
    yield f"http://{server.host}:{server.port}"
    server.shutdown()


def _histo(samples, name, labels=frozenset()):
    """(sorted bucket (le, cum) list, count, sum) for one histogram
    child."""
    buckets = []
    for (n, lab), v in samples.items():
        if n == name + "_bucket" and labels <= lab:
            le = dict(lab)["le"]
            buckets.append((float("inf") if le == "+Inf" else float(le),
                            v))
    count = samples[(name + "_count", labels)]
    total = samples[(name + "_sum", labels)]
    return sorted(buckets), count, total


@pytest.mark.quick
def test_metrics_scrape_counters_and_histogram(served_engine):
    url = served_engine
    _post(url + "/generate", {"prompt_ids": PROMPT, "max_new_tokens": 3})
    text1, ctype = _get(url + "/metrics")
    assert ctype.startswith("text/plain")
    assert "version=0.0.4" in ctype
    s1, types1 = parse_exposition(text1)

    _post(url + "/generate", {"prompt_ids": PROMPT, "max_new_tokens": 3})
    text2, _ = _get(url + "/metrics")
    s2, types2 = parse_exposition(text2)

    # counter monotonicity across the two generate calls
    req_key = ("dwt_http_requests_total",
               frozenset({("route", "/generate"), ("code", "200")}))
    assert req_key in s1 and s2[req_key] == s1[req_key] + 1
    tok_key = ("dwt_http_generated_tokens_total", frozenset())
    assert s2[tok_key] == s1[tok_key] + 3
    # EVERY counter sample is monotone between the scrapes
    for (name, labels), v in s1.items():
        fam = name[:-len("_bucket")] if name.endswith("_bucket") else name
        fam = fam[:-len("_count")] if fam.endswith("_count") else fam
        fam = fam[:-len("_sum")] if fam.endswith("_sum") else fam
        if types1.get(name) == "counter" and (name, labels) in s2:
            assert s2[(name, labels)] >= v, name

    # histogram sanity: cumulative buckets, +Inf present, _count/_sum
    # consistent with the observations.  Counts are DELTAS between the
    # scrapes — the registry is process-global and other tests in the
    # suite observe into it too.
    lab = frozenset({("route", "/generate")})
    _, count1, total1 = _histo(s1, "dwt_http_request_seconds", lab)
    buckets, count, total = _histo(s2, "dwt_http_request_seconds", lab)
    assert buckets, "no histogram buckets rendered"
    assert buckets[-1][0] == float("inf"), "+Inf bucket missing"
    cums = [c for _, c in buckets]
    assert cums == sorted(cums), "buckets must be cumulative"
    assert cums[-1] == count            # +Inf bucket == _count
    assert count == count1 + 1          # one generate between scrapes
    assert total >= total1 >= 0         # _sum is monotone
    # _sum stays consistent with the bucket layout's value range
    assert total - total1 <= 60.0 + 1e-9   # one obs <= top finite bucket
                                           # (requests here take < 60 s)

    # the standard series families render even before their subsystems
    # run: batching + monitor + stage families are present
    assert types2.get("dwt_batching_queue_depth_requests") == "gauge"
    mem_total = ("dwt_monitor_host_memory_bytes",
                 frozenset({("kind", "total")}))
    assert s2[mem_total] > 0


def test_metrics_endpoint_never_500s_on_statless_backend(served_engine):
    # plain engines have no .stats(); the scrape still renders
    text, _ = _get(served_engine + "/metrics")
    parse_exposition(text)


def test_worker_metrics_server():
    """The standalone worker /metrics endpoint (worker_main
    --metrics-port): a MetricsHTTPServer over render_worker exposes the
    stage series for the worker's StageStats."""
    from distributed_inference_demo_tpu.runtime.stats import StageStats
    from distributed_inference_demo_tpu.telemetry import MetricsHTTPServer
    from distributed_inference_demo_tpu.telemetry import catalog

    st = StageStats("worker")
    st.record_compute(0.01)
    st.record_recv(0.002, 1234)
    srv = MetricsHTTPServer(lambda: catalog.render_worker(st, "w9"),
                            port=0)
    srv.start()
    try:
        text, ctype = _get(f"http://{srv.host}:{srv.port}/metrics")
        assert ctype.startswith("text/plain")
        samples, _ = parse_exposition(text)
        lab = frozenset({("role", "worker"), ("device", "w9")})
        assert samples[("dwt_stage_steps_total", lab)] == 1
        assert samples[("dwt_stage_recv_bytes_total", lab)] == 1234
        # non-/metrics paths 404 without breaking the loop
        req = urllib.request.Request(
            f"http://{srv.host}:{srv.port}/other")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 404
    finally:
        srv.shutdown()


def test_worker_metrics_server_debugz():
    """The worker-side GET /debugz (worker_main --metrics-port): JSON
    flight/postmortem state next to the text /metrics scrape."""
    from distributed_inference_demo_tpu.runtime.stats import StageStats
    from distributed_inference_demo_tpu.telemetry import (
        FlightRecorder, MetricsHTTPServer, set_flight_recorder)
    from distributed_inference_demo_tpu.telemetry import catalog

    fr = FlightRecorder(proc="w9", max_events=16)
    set_flight_recorder(fr)
    fr.record("hop_recv", rid=1, step=2)
    st = StageStats("worker")

    def debugz():
        return {"device_id": "w9",
                "flight": {"total": fr.total, "tail": fr.tail(8)}}

    srv = MetricsHTTPServer(lambda: catalog.render_worker(st, "w9"),
                            port=0, debug_provider=debugz)
    srv.start()
    try:
        text, ctype = _get(f"http://{srv.host}:{srv.port}/debugz")
        assert ctype.startswith("application/json")
        dz = json.loads(text)
        assert dz["device_id"] == "w9"
        assert dz["flight"]["tail"][0]["kind"] == "hop_recv"
        # the metrics path still serves text exposition alongside
        text, ctype = _get(f"http://{srv.host}:{srv.port}/metrics")
        assert ctype.startswith("text/plain")
        parse_exposition(text)
    finally:
        srv.shutdown()
        set_flight_recorder(None)


# -- registry / class unit tests -------------------------------------------

def test_counter_rejects_negative_and_duplicate_names():
    reg = Registry()
    c = Counter("dwt_http_x_requests_total", "x", ("route",))
    reg.register(c)
    with pytest.raises(MetricError):
        reg.register(Counter("dwt_http_x_requests_total", "again"))
    with pytest.raises(MetricError):
        c.inc(-1, route="a")
    with pytest.raises(MetricError):
        c.inc(1, wrong_label="a")
    c.inc(2, route="a")
    c.labels(route="a").inc()
    assert list(c.samples()) == [("", (("route", "a"),), 3.0)]


def test_gauge_callback_and_default_render():
    g = Gauge("dwt_batching_depth_requests", "live depth")
    assert list(g.samples()) == [("", (), 0.0)]    # renders before set
    g.set_function(lambda: 7)
    assert list(g.samples()) == [("", (), 7.0)]


def test_histogram_bucket_edges():
    h = Histogram("dwt_http_y_seconds", "y", buckets=(0.1, 1.0))
    h.observe(0.1)     # le == bound lands IN the bucket (le semantics)
    h.observe(0.5)
    h.observe(99.0)    # overflows to +Inf only
    rows = list(h.samples())
    by_suffix = {}
    for suffix, labels, v in rows:
        by_suffix.setdefault(suffix, []).append((labels, v))
    les = {dict(l)["le"]: v for l, v in by_suffix["_bucket"]}
    assert les == {"0.1": 1.0, "1": 2.0, "+Inf": 3.0}
    assert by_suffix["_count"] == [((), 3.0)]
    assert abs(by_suffix["_sum"][0][1] - 99.6) < 1e-9


def test_render_escapes_and_formats():
    reg = Registry()
    g = Gauge("dwt_stage_z_seconds", 'help with "quotes"\nand newline',
              ("role",))
    reg.register(g)
    g.set(1.5, role='we"ird\nrole')
    text = reg.render()
    assert '\\n' in text.splitlines()[0]           # escaped help
    assert 'role="we\\"ird\\nrole"' in text
    assert text.endswith("\n")
    parse_exposition(text)
