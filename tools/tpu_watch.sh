#!/bin/bash
# Tunnel-recovery watcher for the incremental measurement session.
#
# Loops: when the axon tunnel answers a REAL compute probe, runs
# tools/measure_session.py (one bench leg per subprocess, artifact
# committed after every leg).  When the session reports all-legs-done,
# runs the perf probes once (decode profile / int8 dequant / sampling
# cost) and exits.  A wedged tunnel just means another nap.
#
#   nohup bash tools/tpu_watch.sh >> /tmp/tpu_watch.log 2>&1 &
cd "$(dirname "$0")/.."
# default artifact comes from bench.py's PRIOR_ARTIFACT_NAME (one owner,
# bumped per round) so an argument-less watcher can't write a new round's
# legs into an old round's artifact
ART="${1:-$(python -c 'import bench; print(bench.PRIOR_ARTIFACT_NAME)' 2>/dev/null || echo BENCH_SELF_r05.json)}"
# probe log named after the artifact's round tag (BENCH_SELF_r04.json ->
# PROBES_r04.log) so a future round's watcher doesn't mislabel its output
TAG=$(basename "$ART" .json); TAG=${TAG#BENCH_SELF_}
PLOG="PROBES_${TAG}.log"
while true; do
  echo "=== watch tick $(date -u +%H:%M:%S) ==="
  python tools/measure_session.py --artifact "$ART"
  rc=$?
  if [ "$rc" -eq 0 ]; then
    echo "=== all legs done; running probes ==="
    { echo "# Probe output from tools/tpu_watch.sh at $(date -u +%FT%TZ)."
      echo "# (bench legs live in $ART; this file is the probe log)"
      for p in decode_profile_probe int8_dequant_probe sampling_cost_probe; do
        [ -f "tools/$p.py" ] || continue
        echo "=== probe $p $(date -u +%H:%M:%S) ==="
        timeout 2400 python "tools/$p.py" 2>&1
      done
    } | tee "$PLOG"
    git add "$PLOG"
    if ! git commit -m "Record $TAG probe log" -- "$PLOG"; then
      # a stale session process may hold index.lock; one retry after a
      # beat, and a second failure is reported instead of exit 0 lying
      echo "probe-log commit failed; retrying in 10s"
      sleep 10
      git add "$PLOG"
      git commit -m "Record $TAG probe log" -- "$PLOG" || {
        echo "probe-log commit failed twice; $PLOG left uncommitted"
        exit 1
      }
    fi
    echo "=== watcher done ==="
    exit 0
  fi
  sleep 180
done
