"""Fetch / inspect §20 workload-sketch artifacts.

The measurement half of the auto-planner loop (ROADMAP item 3): a
replica or gateway serves its workload sketch at ``GET /sketch``
(canonical JSON — byte-deterministic for an identical request trace);
this tool fetches or reads one, re-validates it against the planner's
pinned schema, and writes/prints it as a committable artifact.

Usage::

    python tools/sketch.py --url http://127.0.0.1:8000        # GET /sketch
    python tools/sketch.py --url 127.0.0.1:8000 -o sketch.json
    python tools/sketch.py --file sketch.json --planner-input
    cat sketch.json | python tools/sketch.py --stdin

``--planner-input`` prints the distilled WorkloadSketch the planner
consumes (ctx tokens, arrival rate, prefix share) — the exact values
``planner.plan_from_sketch`` feeds into ``plan_partition``.
"""

import argparse
import json
import pathlib
import sys
import urllib.request

# repo root on sys.path when run as a script from anywhere
_ROOT = str(pathlib.Path(__file__).resolve().parents[1])
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from distributed_inference_demo_tpu.planner import (SketchError,
                                                    load_workload_sketch)
from distributed_inference_demo_tpu.telemetry.profiling import \
    render_sketch


def fetch_sketch(url: str, timeout: float = 10.0) -> str:
    """GET /sketch from a replica or gateway; ``url`` may be a bare
    ``host:port``.  Returns the body VERBATIM (the canonical bytes —
    re-dumping here would break byte-determinism)."""
    if "://" not in url:
        url = "http://" + url
    if not url.rstrip("/").endswith("/sketch"):
        url = url.rstrip("/") + "/sketch"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fetch/inspect a workload-sketch artifact")
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="replica or gateway base URL "
                                   "(host:port accepted)")
    src.add_argument("--file", help="read an artifact from a JSON file")
    src.add_argument("--stdin", action="store_true",
                     help="read an artifact from stdin")
    ap.add_argument("-o", "--out", help="write the canonical artifact "
                                        "to this path (atomic-ish)")
    ap.add_argument("--planner-input", action="store_true",
                    help="print the distilled planner workload input "
                         "instead of the raw artifact")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)

    if args.url:
        raw = fetch_sketch(args.url, timeout=args.timeout)
    elif args.stdin:
        raw = sys.stdin.read()
    else:
        with open(args.file) as f:
            raw = f.read()

    try:
        obj = json.loads(raw)
    except ValueError as e:
        print(f"error: artifact is not JSON: {e}", file=sys.stderr)
        return 2
    # validate against the planner's pinned schema BEFORE writing: a
    # committed artifact the planner later rejects helps nobody
    try:
        ws = load_workload_sketch(obj)
    except SketchError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2

    canonical = render_sketch(obj)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(canonical)
        import os
        os.replace(tmp, args.out)
        print(f"wrote {args.out} ({len(canonical)} bytes, "
              f"{ws.requests} requests)", file=sys.stderr)

    if args.planner_input:
        print(json.dumps({
            "ctx_tokens": ws.ctx_tokens,
            "arrival_rate_per_s": round(ws.arrival_rate, 6),
            "prefix_share": round(ws.prefix_share, 6),
            "prompt_p50": ws.prompt_p50, "prompt_p95": ws.prompt_p95,
            "decode_p50": ws.decode_p50, "decode_p95": ws.decode_p95,
            "requests": ws.requests, "window_s": ws.window_s,
            "tenants": ws.tenants,
        }, sort_keys=True, separators=(",", ":")))
    elif not args.out:
        print(canonical)
    return 0


if __name__ == "__main__":
    sys.exit(main())
