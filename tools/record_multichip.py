"""Record the multi-chip dryrun as a round artifact, BYTE-IDENTICAL to
the driver's rewrite.

Four rounds in a row the working tree showed ``M MULTICHIP_r*.json``
after a driver re-run (VERDICT r5 item 6, r4 item 8, r3 item 7, r2):
the builder stamped a ``git_head`` field and a trailing newline the
driver's writer doesn't emit, so the driver's byte-for-byte rewrite of
the SAME passing dryrun registered as a diff.  This writer emits exactly
the driver's format — ``json.dumps({n_devices, rc, ok, skipped, tail},
indent=2)``, ascii-escaped, NO trailing newline — and banks provenance
in a ``<artifact>.head`` sidecar the driver never touches.

Usage::

    python tools/record_multichip.py --out MULTICHIP_r06.json [--n 8]

The byte format is pinned by ``tests/test_measure_tools.py`` against the
committed ``MULTICHIP_r05.json`` (itself a driver rewrite).
"""

import argparse
import json
import os
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def render_artifact(n_devices: int, rc: int, tail: str,
                    skipped: bool = False) -> str:
    """The driver's exact serialization: key order, indent=2, ascii
    escapes, no trailing newline, no provenance fields."""
    return json.dumps({"n_devices": n_devices, "rc": rc, "ok": rc == 0,
                       "skipped": skipped, "tail": tail}, indent=2)


def run_dryrun(n_devices: int, timeout: int = 1800):
    """``dryrun_multichip(n)`` in a fresh CPU-forced subprocess;
    returns (rc, combined output)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             f"import __graft_entry__ as g; g.dryrun_multichip({n_devices})"],
            cwd=str(REPO), env=env, capture_output=True, text=True,
            timeout=timeout)
    except subprocess.TimeoutExpired as e:
        # a hung dryrun must still produce an ok:false artifact — an
        # unhandled crash here is exactly the unrecorded-run failure
        # mode this tool exists to eliminate
        out = (e.stdout.decode() if isinstance(e.stdout, bytes)
               else e.stdout) or ""
        return 124, out + f"\n--- timed out after {timeout}s ---"
    out = p.stdout
    if p.returncode != 0 and p.stderr:
        out += ("\n--- stderr tail ---\n" + p.stderr[-2000:])
    return p.returncode, out


def git_head() -> str:
    p = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                       cwd=str(REPO), capture_output=True, text=True)
    return p.stdout.strip()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True,
                    help="artifact path, e.g. MULTICHIP_r06.json")
    ap.add_argument("--n", type=int, default=8)
    args = ap.parse_args()

    rc, tail = run_dryrun(args.n)
    out_path = REPO / args.out
    out_path.write_text(render_artifact(args.n, rc, tail))
    # provenance rides in a sidecar the driver's rewrite never touches,
    # so the artifact itself stays byte-stable across re-runs
    head = git_head()
    if head:
        out_path.with_suffix(out_path.suffix + ".head").write_text(
            head + "\n")
    print(f"record_multichip: wrote {out_path.name} "
          f"(rc={rc}, ok={rc == 0}, head={head or '?'})")
    return 0 if rc == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
