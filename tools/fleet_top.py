#!/usr/bin/env python
"""Live fleet SLO view over the gateway's federated metrics page.

``fleet_top`` is ``top(1)`` for the serving fleet: it polls the
gateway's ``GET /metrics/fleet`` (every replica's ``/metrics``
re-labeled with ``replica=`` and merged — see
``runtime/gateway/federation.py``) and renders one row per
tenant x replica:

- request / token counts and the goodput ratio (tokens inside the
  TTFT/TPOT SLO vs total, from ``dwt_slo_good_tokens_total`` /
  ``dwt_slo_tokens_total``);
- error-budget burn rates per window (``dwt_slo_burn_rate_ratio``,
  5m and 1h — both > 1.0 means the budget is burning faster than it
  refills);
- TTFT p95 estimated from the ``dwt_slo_ttft_seconds`` histogram
  buckets (upper-bound of the bucket crossing the 95th percentile);
- migrated-request counts, plus each replica's scrape age so a stale
  section is visible as staleness, not as a frozen tenant;
- with ``--kv``, a per-replica KV tier-occupancy section (host ring /
  disk segment resident vs capacity, hit and demote/promote counters,
  from the ``dwt_kvcache_tier_*`` series — docs/DESIGN.md §21); crash-
  safe when a fleet exports no tier series at all.

Stdlib only (urllib + ANSI), same constraint as every ``tools/``
script.  ``--once`` prints a single snapshot and exits — the mode the
tests (and cron jobs) use; without it the screen redraws every
``--interval`` seconds until Ctrl-C.

Usage::

    python tools/fleet_top.py --gateway 127.0.0.1:8100
    python tools/fleet_top.py --gateway 127.0.0.1:8100 --once
"""

from __future__ import annotations

import argparse
import re
import sys
import time
import urllib.request
from typing import Dict, List, Tuple

_LABEL_RE = re.compile(r'(\w+)="((?:\\.|[^"\\])*)"')
_UNESCAPE = {"\\\\": "\\", '\\"': '"', "\\n": "\n"}


def _unescape(v: str) -> str:
    return re.sub(r'\\[\\"n]', lambda m: _UNESCAPE[m.group(0)], v)


def parse_metrics(text: str) -> List[Tuple[str, Dict[str, str], float]]:
    """Exposition text → ``[(name, labels, value), ...]`` samples."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        brace = line.find("{")
        if brace != -1:
            close = line.rfind("}")
            if close == -1:
                continue
            name = line[:brace]
            labels = {k: _unescape(v) for k, v in
                      _LABEL_RE.findall(line[brace + 1:close])}
            rest = line[close + 1:].strip()
        else:
            parts = line.split(None, 1)
            if len(parts) != 2:
                continue
            name, rest = parts[0], parts[1]
            labels = {}
        try:
            value = float(rest.split()[0])
        except (ValueError, IndexError):
            continue
        out.append((name, labels, value))
    return out


def _hist_p95(buckets: Dict[float, float]) -> float:
    """p95 upper-bound from cumulative ``le`` buckets (NaN when empty)."""
    if not buckets:
        return float("nan")
    les = sorted(buckets)
    total = buckets[les[-1]]
    if total <= 0:
        return float("nan")
    want = 0.95 * total
    for le in les:
        if buckets[le] >= want:
            return le
    return les[-1]


def fleet_rows(samples) -> List[dict]:
    """Samples → one row dict per (tenant, replica), sorted."""
    rows: Dict[Tuple[str, str], dict] = {}
    ttft_buckets: Dict[Tuple[str, str], Dict[float, float]] = {}

    def row(labels: dict) -> dict:
        key = (labels.get("tenant", "?"), labels.get("replica", "-"))
        return rows.setdefault(key, {
            "tenant": key[0], "replica": key[1], "requests": 0.0,
            "failed": 0.0, "migrated": 0.0, "tokens": 0.0,
            "good_tokens": 0.0, "burn": {}, "ttft_p95_s": float("nan")})

    simple = {"dwt_slo_requests_total": "requests",
              "dwt_slo_failed_requests_total": "failed",
              "dwt_slo_migrated_requests_total": "migrated",
              "dwt_slo_tokens_total": "tokens",
              "dwt_slo_good_tokens_total": "good_tokens"}
    for name, labels, value in samples:
        if name in simple:
            row(labels)[simple[name]] += value
        elif name == "dwt_slo_burn_rate_ratio":
            row(labels)["burn"][labels.get("window", "?")] = value
        elif name == "dwt_slo_ttft_seconds_bucket":
            key = (labels.get("tenant", "?"), labels.get("replica", "-"))
            try:
                le = float(labels.get("le", "inf").replace("+Inf", "inf"))
            except ValueError:
                continue
            ttft_buckets.setdefault(key, {})[le] = value
    for key, buckets in ttft_buckets.items():
        if key in rows:
            rows[key]["ttft_p95_s"] = _hist_p95(buckets)
    for r in rows.values():
        r["goodput"] = (r["good_tokens"] / r["tokens"]
                        if r["tokens"] > 0 else float("nan"))
    return [rows[k] for k in sorted(rows)]


def profile_rows(samples, top: int = 10) -> List[dict]:
    """Top dispatch signatures by sampled p95, one row per
    (signature, replica), from the federated ``dwt_profile_*`` series
    (docs/DESIGN.md §20).  A replica exposing no profiling series (old
    build, or DWT_PROFILE_SAMPLE_N=0) simply contributes no rows —
    never a crash."""
    buckets: Dict[Tuple[str, str], Dict[float, float]] = {}
    sums: Dict[Tuple[str, str], float] = {}
    counts: Dict[Tuple[str, str], float] = {}
    dispatches: Dict[Tuple[str, str], float] = {}
    for name, labels, value in samples:
        key = (labels.get("signature", "?"), labels.get("replica", "-"))
        if name == "dwt_profile_dispatch_seconds_bucket":
            try:
                le = float(labels.get("le", "inf").replace("+Inf", "inf"))
            except ValueError:
                continue
            buckets.setdefault(key, {})[le] = value
        elif name == "dwt_profile_dispatch_seconds_sum":
            sums[key] = value
        elif name == "dwt_profile_dispatch_seconds_count":
            counts[key] = value
        elif name == "dwt_profile_dispatches_total":
            dispatches[key] = value
    rows = []
    for key, b in buckets.items():
        n = counts.get(key, 0.0)
        rows.append({
            "signature": key[0], "replica": key[1],
            "samples": int(n),
            "dispatches": int(dispatches.get(key, 0.0)),
            "p95_s": _hist_p95(b),
            "mean_s": (sums.get(key, 0.0) / n) if n > 0
                      else float("nan")})
    rows.sort(key=lambda r: (-(r["p95_s"] if r["p95_s"] == r["p95_s"]
                               else -1.0), r["signature"], r["replica"]))
    return rows[:top]


def render_profile(rows: List[dict]) -> str:
    hdr = (f"{'SIGNATURE':<34} {'REPLICA':<22} {'DISP':>8} "
           f"{'SAMP':>6} {'MEANms':>8} {'P95ms':>8}")
    lines = ["", "top dispatch signatures by p95 (sampled):",
             hdr, "-" * len(hdr)]
    if not rows:
        lines.append("(no dwt_profile_* series exported — profiling "
                     "disabled or pre-§20 replicas)")
    for r in rows:
        mean = (f"{r['mean_s'] * 1e3:.2f}"
                if r["mean_s"] == r["mean_s"] else "-")
        p95 = (f"{r['p95_s'] * 1e3:.2f}"
               if r["p95_s"] == r["p95_s"] else "-")
        lines.append(
            f"{r['signature']:<34.34} {r['replica']:<22.22} "
            f"{r['dispatches']:>8} {r['samples']:>6} "
            f"{mean:>8} {p95:>8}")
    return "\n".join(lines)


def kv_tier_rows(samples) -> List[dict]:
    """Per-replica KV tier occupancy from the federated
    ``dwt_kvcache_tier_*`` series (docs/DESIGN.md §21): resident
    blocks/bytes vs capacity for the host ring and disk segment, plus
    the cumulative demote/promote counters.  A replica exposing no
    tier series (tiering off, or a pre-§21 build) contributes no rows
    — never a crash."""
    per: Dict[str, dict] = {}

    def rep(labels: dict) -> dict:
        return per.setdefault(labels.get("replica", "-"), {
            "replica": labels.get("replica", "-"),
            "tiers": {}, "demoted": 0.0, "promoted": 0.0,
            "spilled": 0.0, "dropped": 0.0})

    def tier(labels: dict) -> dict:
        return rep(labels)["tiers"].setdefault(
            labels.get("tier", "?"),
            {"blocks": 0.0, "bytes": 0.0, "cap": 0.0, "hits": 0.0})

    gauges = {"dwt_kvcache_tier_resident_blocks": "blocks",
              "dwt_kvcache_tier_resident_bytes": "bytes",
              "dwt_kvcache_tier_capacity_bytes": "cap",
              "dwt_kvcache_tier_hits_total": "hits"}
    counters = {"dwt_kvcache_tier_demoted_blocks_total": "demoted",
                "dwt_kvcache_tier_promoted_blocks_total": "promoted",
                "dwt_kvcache_tier_spilled_blocks_total": "spilled",
                "dwt_kvcache_tier_dropped_blocks_total": "dropped"}
    for name, labels, value in samples:
        if name in gauges:
            tier(labels)[gauges[name]] = value
        elif name in counters:
            rep(labels)[counters[name]] += value
    return [per[k] for k in sorted(per)]


def render_kv(rows: List[dict]) -> str:
    hdr = (f"{'REPLICA':<22} {'TIER':<5} {'BLOCKS':>7} {'RES_MB':>8} "
           f"{'CAP_MB':>8} {'USE%':>6} {'HITS':>7} {'DEM':>7} "
           f"{'PRO':>7} {'SPILL':>6} {'DROP':>6}")
    lines = ["", "kv tier occupancy (host ring / disk segment):",
             hdr, "-" * len(hdr)]
    if not rows:
        lines.append("(no dwt_kvcache_tier_* series exported — tiering "
                     "off or pre-§21 replicas)")
    for r in rows:
        for tname in sorted(r["tiers"]):
            t = r["tiers"][tname]
            use = (100 * t["bytes"] / t["cap"]) if t["cap"] > 0 else None
            lines.append(
                f"{r['replica']:<22.22} {tname:<5.5} "
                f"{int(t['blocks']):>7} {t['bytes'] / 2**20:>8.2f} "
                f"{t['cap'] / 2**20:>8.2f} "
                f"{(f'{use:.1f}%' if use is not None else '-'):>6} "
                f"{int(t['hits']):>7} {int(r['demoted']):>7} "
                f"{int(r['promoted']):>7} {int(r['spilled']):>6} "
                f"{int(r['dropped']):>6}")
    return "\n".join(lines)


def scrape_ages(samples) -> Dict[str, float]:
    return {labels.get("replica", "?"): value
            for name, labels, value in samples
            if name == "dwt_gateway_fleet_scrape_age_seconds"}


def _fmt(v: float, pct: bool = False) -> str:
    if v != v:                       # NaN
        return "-"
    return f"{100 * v:.1f}%" if pct else f"{v:.2f}"


def render(rows: List[dict], ages: Dict[str, float]) -> str:
    hdr = (f"{'TENANT':<16} {'REPLICA':<22} {'REQS':>6} {'FAIL':>5} "
           f"{'MIGR':>5} {'TOKENS':>8} {'GOODPUT':>8} {'BURN5m':>7} "
           f"{'BURN1h':>7} {'TTFTp95':>8}")
    lines = [hdr, "-" * len(hdr)]
    for r in rows:
        burn = r["burn"]
        lines.append(
            f"{r['tenant']:<16.16} {r['replica']:<22.22} "
            f"{int(r['requests']):>6} {int(r['failed']):>5} "
            f"{int(r['migrated']):>5} {int(r['tokens']):>8} "
            f"{_fmt(r['goodput'], pct=True):>8} "
            f"{_fmt(burn.get('5m', float('nan'))):>7} "
            f"{_fmt(burn.get('1h', float('nan'))):>7} "
            f"{_fmt(r['ttft_p95_s']):>7}s")
    if ages:
        lines.append("")
        lines.append("scrape age: " + "  ".join(
            f"{rid}={age:.1f}s" for rid, age in sorted(ages.items())))
    return "\n".join(lines)


def fetch(base: str, path: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(f"http://{base}{path}",
                                timeout=timeout) as resp:
        return resp.read().decode("utf-8", "replace")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--gateway", required=True,
                    help="gateway host:port (e.g. 127.0.0.1:8100)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh period in seconds (default 2)")
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (no ANSI)")
    ap.add_argument("--profile", action="store_true",
                    help="append the top dispatch signatures by sampled "
                         "p95 (dwt_profile_* series, docs/DESIGN.md §20)")
    ap.add_argument("--profile-top", type=int, default=10,
                    help="rows in the --profile section (default 10)")
    ap.add_argument("--kv", action="store_true",
                    help="append per-replica KV tier occupancy "
                         "(dwt_kvcache_tier_* series, docs/DESIGN.md §21)")
    args = ap.parse_args(argv)
    while True:
        try:
            text = fetch(args.gateway, "/metrics/fleet")
        except Exception as e:
            print(f"fleet_top: cannot scrape {args.gateway}: {e}",
                  file=sys.stderr)
            return 1
        samples = parse_metrics(text)
        page = render(fleet_rows(samples), scrape_ages(samples))
        if args.profile:
            page += "\n" + render_profile(
                profile_rows(samples, top=args.profile_top))
        if args.kv:
            page += "\n" + render_kv(kv_tier_rows(samples))
        if args.once:
            print(page)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + page + "\n")
        sys.stdout.flush()
        try:
            time.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
