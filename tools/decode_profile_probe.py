"""Probe: decompose the large-batch decode step on the real chip.

BENCH_SELF_r03's sweep showed achieved weights-GB/s collapsing with batch
(501 at b8 -> 197 at b64) at tiny context, where cache reads are ~12% of
weight traffic — so the erosion is per-row ACTIVATION work, not HBM
streaming.  This probe separates the suspects:

1. **Batch scaling law**: per-step time at b in {1, 8, 32, 64} under
   greedy (forward + argmax only).  A linear fit t(b) = floor + slope*b
   gives the weight-stream floor (should approach weights_bytes /
   measured HBM GB/s) and the per-row marginal cost.
2. **Sampling tax**: the same step under top-k=7 — the delta vs greedy is
   pure sampling (filtered_logits + categorical).  After the
   approx_max_k change (ops/sampling.py), this should be flat-ish in
   batch; if it still grows, the next suspect is `jax.random.categorical`
   's [b, vocab] gumbel draw.
3. **kth-value microbench in isolation**: lax.top_k's sort vs the
   iterative argmax-and-mask path (ops.sampling.kth_largest) vs a bare
   argmax on [b, 32000] f32 logits — the direct on-chip comparison
   behind the filtered_logits small-k gate.

Run on the real device: ``python tools/decode_profile_probe.py``
(the tunnel-recovery watcher runs it automatically, tools/tpu_session.sh).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.telemetry.profiling import \
    dispatch_signature

try:        # `python tools/decode_profile_probe.py` vs `-m tools....`
    from probe_artifact import emit_signatures
except ImportError:
    from tools.probe_artifact import emit_signatures

BATCHES = (1, 8, 32, 64)
NEW = 128


def step_ms(engine, batch: int) -> float:
    """Decode-ONLY per-step ms: prefill runs outside the timed region so
    the batch-scaling fit isolates the decode step (whole-generate /
    NEW would fold per-batch prefill cost into the slope)."""
    prompt = (np.arange(batch * 64).reshape(batch, 64) % 1000).astype(
        np.int32)
    engine.generate(prompt, NEW, seed=0)               # compile both jits
    cache = engine.new_cache(batch)
    logits, cache = engine._run_prefill(jnp.asarray(prompt), cache)
    np.asarray(logits)                                 # fence
    t0 = time.perf_counter()
    toks, _, _ = engine._decode(engine.params, logits, cache,
                                jax.random.PRNGKey(0),
                                engine._eos_scalar(), NEW, False)
    np.asarray(toks)                                   # axon-safe fence
    return (time.perf_counter() - t0) / NEW * 1000


def main():
    cfg = get_model_config("tinyllama-1.1b")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    weights_gb = params.nbytes() / 1e9

    print(f"== decode step decomposition (tinyllama bf16, "
          f"weights {weights_gb:.2f} GB, new={NEW}) ==", flush=True)
    rows = {}
    for name, samp in (("greedy", SamplingParams(greedy=True)),
                       ("topk7", SamplingParams(temperature=0.7, top_k=7))):
        eng = InferenceEngine(cfg, params, max_seq=192, sampling=samp)
        for b in BATCHES:
            ms = step_ms(eng, b)
            rows[(name, b)] = ms
            gbs = weights_gb / (ms / 1000)
            print(f"b={b:3d} {name:7s} {ms:7.2f} ms/step  "
                  f"weights-GB/s={gbs:6.1f}", flush=True)

    # linear fit of the greedy curve: floor + slope*b
    bs = np.asarray(BATCHES, np.float64)
    ts = np.asarray([rows[("greedy", b)] for b in BATCHES])
    slope, floor = np.polyfit(bs, ts, 1)
    print(f"greedy fit: floor={floor:.2f} ms (weight stream => "
          f"{weights_gb / (floor / 1000):.0f} GB/s), "
          f"slope={slope * 1000:.1f} us/row", flush=True)
    for b in BATCHES:
        tax = rows[("topk7", b)] - rows[("greedy", b)]
        print(f"b={b:3d} sampling tax {tax:+.2f} ms/step", flush=True)

    # observatory artifact: the same numbers keyed by dispatch
    # signature (mergeable with /debugz snapshots + bench extras)
    emit_signatures(
        [(dispatch_signature(f"probe_decode_{name}", batch=b, chunk=NEW),
          {"mean_ms": ms,
           "weights_gbs": weights_gb / (ms / 1000)})
         for (name, b), ms in sorted(rows.items())],
        extra={"probe": "decode_profile", "weights_gb": weights_gb})

    print("== kth-value microbench on [b, 32000] f32 ==", flush=True)

    def bench(fn, logits, reps=50):
        fn(logits).block_until_ready()
        out = None
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(logits)
        np.asarray(out)          # axon-safe fence
        return (time.perf_counter() - t0) / reps * 1000

    from distributed_inference_demo_tpu.ops.sampling import (
        SamplingParams as SP, filtered_logits, kth_largest, sample_logits,
        topk_vals_idx)
    key = jax.random.PRNGKey(0)
    samp7 = SP(temperature=0.7, top_k=7)

    def full_vocab_draw(k, x):
        # the pre-r04 sampler: mask the vocab, gumbel over [b, V]
        return jax.random.categorical(k, filtered_logits(x, samp7), axis=-1)

    def fused_draw(k, x):
        # the r04 sampler: k argmax passes -> categorical over [b, k]
        return sample_logits(x, k, samp7)

    variants = {
        "top_k": jax.jit(lambda x: jax.lax.top_k(x, 7)[0][..., -1]),
        "iter_kth": jax.jit(lambda x: kth_largest(x, 7)[..., 0]),
        "iter_topk_vi": jax.jit(lambda x: topk_vals_idx(x, 7)[0]),
        "argmax": jax.jit(lambda x: jnp.argmax(x, -1)),
        # the OTHER half of the sampling tax: the [b, vocab] gumbel draw
        # (the key rides in as an argument — a baked constant key would
        # let XLA constant-fold the whole noise tensor out of the timing)
        "categorical": (lambda f: lambda x: f(key, x))(jax.jit(
            lambda k, x: jax.random.categorical(k, x, axis=-1))),
        # end-to-end samplers, old vs new (same distribution, different
        # draw shape: [b, V] gumbel vs [b, 7])
        "full_draw": (lambda f: lambda x: f(key, x))(jax.jit(
            full_vocab_draw)),
        "fused_draw": (lambda f: lambda x: f(key, x))(jax.jit(fused_draw)),
    }
    for b in BATCHES:
        logits = jax.random.normal(jax.random.PRNGKey(1), (b, 32000),
                                   jnp.float32)
        line = " ".join(f"{name}={bench(fn, logits):6.3f}ms"
                        for name, fn in variants.items())
        print(f"b={b:3d} {line}", flush=True)


if __name__ == "__main__":
    main()
