"""Incremental TPU measurement session: one bench leg per subprocess,
merged into the round's self-artifact and committed AFTER EACH LEG.

Why not one monolithic ``python bench.py`` run: the axon tunnel wedges
mid-session (r04's first full run lost 6 legs to a wedge that began
~15 minutes in; r03 lost its entire driver bench the same way).  This
harness makes every completed leg durable immediately:

  for each leg missing-or-errored in the artifact:
      1. health-probe the tunnel with REAL compute (a small matmul --
         ``jax.devices()`` answers even when dispatch is wedged)
      2. run ``bench.py --leg <name>`` in a subprocess with its own budget
         (bench's group-killable spawner: stderr tail on failure, survives
         D-state children)
      3. merge the result into the artifact, recompute derived fields,
         git-commit the artifact (path-scoped)
      4. a failed health probe ends the session; the next invocation
         (tools/tpu_watch.sh loops on this) resumes at the first missing leg

Before any full-budget leg runs, a MICRO PREPASS sweeps every leg at its
smallest meaningful shape (``bench.py --leg X --micro``, 1 round, ~15 s
of measurement each) and commits the results under ``extras.micro`` — a
short healthy tunnel window banks a coarse number for ALL legs
(including ones whose full budgets would never fit the window) before
the session gambles on full-budget passes.  ``--no-micro`` skips it.

Usage: ``python tools/measure_session.py [--artifact BENCH_SELF_r04.json]
[--legs a,b,c] [--force a,b] [--no-micro]``
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402  (leg spawner + group-killable runner)

# leg -> subprocess budget (s).  Generous: a leg is only attempted when
# the tunnel just answered a compute probe, and a hung leg ends the
# session anyway (the watcher retries later).
LEG_BUDGETS = {
    "roofline_probe": 600,
    "headline": 1200,
    "headline_int8": 1200,
    "decode_fused": 1200,
    "speculative": 1500,
    "prompt_lookup": 1500,
    "planner_pipeline": 1800,
    "long_context": 1800,
    "long_context_sp": 1800,
    "disagg": 1500,
    "gateway_routing": 1500,
    # two replica engines through three routed phases (reference soak,
    # mid-soak failover, documented loss) — budget like gateway_routing
    "stream_failover": 1500,
    "flagship_int8": 2400,
    "batching": 2400,
    # two full engines (serialized baseline + mixed) with background
    # saturation rows and a fixed-arrival measured stream — budget like
    # batching
    "mixed_batching": 2400,
    # three serving configurations (spec-only, mixed-only, spec x mixed)
    # over the same fixed-arrival stream — budget like mixed_batching
    "spec_mixed": 2400,
    "prefix_reuse": 1800,
    # two engine builds (re-prefill reference + tiered) over two routed
    # rounds each — budget like prefix_reuse
    "tiered_prefix": 1800,
    "paged_decode": 1800,
    "serving_relative": 1800,
    # the full-budget sweep now runs the promoted b8/32/64 x
    # {bf16,int8,int4} grid (9 engine builds) — budget like the other
    # multi-engine legs
    "sweep": 2400,
    "flagship_bf16": 2400,
    "pipeline": 1500,
    "prefill_long": 1800,
    "moe": 1800,
    "multimodal": 1500,
    "int4": 2400,
}
DEFAULT_LEGS = list(LEG_BUDGETS)

# micro-prepass subprocess budget: the SHAPE measures in ~15 s; the
# budget leaves room for compile through a slow tunnel.  One bad micro
# leg must not eat the window the prepass exists to exploit.
MICRO_BUDGET = int(os.environ.get("DWT_MICRO_BUDGET_S", "300"))


_PROBE_SRC = """
import time, jax, jax.numpy as jnp
x = jnp.ones((1024, 1024), jnp.bfloat16)
(x @ x).block_until_ready()
big = jnp.ones((1 << 29,), jnp.bfloat16)   # 1 GiB

def red(v):
    # each iteration mixes the scan input into the read so the reduce is
    # NOT loop-invariant (XLA LICM could hoist an invariant sum and the
    # probe would divide 1 GiB of real traffic by 16 GiB)
    def rep(acc, x):
        return acc + jnp.sum((v + x).astype(jnp.float32)), None
    return jax.lax.scan(rep, 0.0, jnp.arange(16, dtype=v.dtype))[0]

f = jax.jit(red)
float(f(big))
t0 = time.perf_counter()
float(f(big))
dt = time.perf_counter() - t0
print('hbm_gbs=%.1f' % (big.nbytes * 16 / dt / 1e9))
print('platform=' + jax.devices()[0].platform)
"""


def tunnel_healthy(timeout=240):
    """A REAL dispatch probe: 1k matmul + block_until_ready, AND the
    platform must actually be a TPU — if the tunnel drops and jax falls
    back to CPU, the matmul succeeds in milliseconds and every leg would
    happily commit CPU-speed numbers over the TPU measurements.

    Also times a 16 GiB HBM read so the session accumulates a bandwidth
    bracket AROUND every leg (leg N's post-probe is leg N+1's pre-probe).
    The r04 artifact's headline beat its own 'measured ceiling' because
    the one roofline probe ran while the tunnel was degrading; the
    ceiling is now the MAX over all session probes.  Returns
    ``(healthy, hbm_gbs_or_None)``."""
    rc, out, _ = bench._run_group_killable(
        [sys.executable, "-c", _PROBE_SRC], timeout)
    ok = rc == 0 and "platform=tpu" in (out or "")
    gbs = None
    for line in (out or "").splitlines():
        if line.startswith("hbm_gbs="):
            try:
                gbs = float(line.split("=", 1)[1])
            except ValueError:
                pass
    return ok, gbs


def load_artifact(path: Path) -> dict:
    if path.exists():
        return json.loads(path.read_text())
    return {"note": "", "metric": None, "value": None,
            "unit": "tokens/sec", "vs_baseline": None,
            "headline": {}, "extras": {}}


def leg_result(artifact: dict, leg: str):
    if leg == "headline":
        return artifact.get("headline") or None
    return (artifact.get("extras") or {}).get(leg)


def leg_done(artifact: dict, leg: str) -> bool:
    r = leg_result(artifact, leg)
    return isinstance(r, dict) and bool(r) and "error" not in r


MAX_ATTEMPTS = 3


def leg_exhausted(artifact: dict, leg: str) -> bool:
    """An errored leg is retried up to MAX_ATTEMPTS times (transient
    tunnel faults), then left as its recorded error — without this bound
    a deterministic failure would keep the watcher re-running an
    expensive leg (and committing) every tick, forever."""
    r = leg_result(artifact, leg)
    if leg == "headline":
        # headline errors are recorded aside (never clobber the measured
        # top-level value), so the attempt count lives there
        r = (artifact.get("extras") or {}).get("headline_rerun")
    return (isinstance(r, dict) and "error" in r
            and r.get("attempts", 1) >= MAX_ATTEMPTS)


def merge(artifact: dict, leg: str, result: dict, params: dict) -> dict:
    if "error" in result and leg_done(artifact, leg):
        # never clobber a measured result with an error dict (a --force
        # re-run that hit a wedge would otherwise destroy data in git);
        # record the failed attempt alongside — carrying the attempts
        # counter so repeatedly-failing forced re-runs register in the
        # retry ledger like any other errored leg
        prev = (artifact.get("extras") or {}).get(f"{leg}_rerun")
        if isinstance(prev, dict) and "error" in prev:
            result["attempts"] = prev.get("attempts", 1) + 1
        artifact.setdefault("extras", {})[f"{leg}_rerun"] = result
        return artifact
    if leg == "headline":
        if "error" in result:
            prev = (artifact.get("extras") or {}).get("headline_rerun")
            if isinstance(prev, dict) and "error" in prev:
                result["attempts"] = prev.get("attempts", 1) + 1
            artifact.setdefault("extras", {})["headline_rerun"] = result
            return artifact
        artifact["headline"] = result
        # one owner for the metric string / comparability caveats:
        # bench.headline_summary (shared with bench.py main())
        summary = bench.headline_summary(result, params,
                                         result.get("device", "?"))
        artifact["metric"] = summary["metric"]
        artifact["value"] = summary["value"]
        artifact["vs_baseline"] = summary["vs_baseline"]
        artifact.setdefault("extras", {})["baseline"] = summary["baseline"]
    else:
        prev = (artifact.get("extras") or {}).get(leg)
        if "error" in result and isinstance(prev, dict) and "error" in prev:
            result["attempts"] = prev.get("attempts", 1) + 1
        artifact.setdefault("extras", {})[leg] = result

    # measured-ceiling fractions against the DECLARED ceiling:
    # max(session probes, committed best-ever roofline ledger).  The
    # session side is the MAX over the roofline leg and every per-leg
    # health probe (the probes bracket each leg, so a ceiling measured
    # during tunnel degradation can't stay the ceiling); the ledger side
    # persists the best evidence ever seen for the chip, so one degraded
    # session can no longer mint a "ceiling" real workloads beat —
    # frac > 1 is impossible by construction (bench.apply_measured_frac
    # raises the ledger to any achieved rate that exceeds it).
    session = session_ceiling(artifact)
    device = artifact_device(artifact, result)
    bench.apply_declared_ceiling(artifact.get("headline", {}) or {},
                                 artifact.setdefault("extras", {}),
                                 device, session,
                                 source="measure_session probe max")
    return artifact


def artifact_device(artifact: dict, result=None):
    """The device string this artifact's numbers describe — headline
    first (the ledger key must be stable across legs), then any leg's
    stamp, then the just-measured result."""
    cands = [artifact.get("headline") or {}]
    for v in (artifact.get("extras") or {}).values():
        if isinstance(v, dict):
            cands.append(v)
            cands += [p for p in v.get("points", [])
                      if isinstance(p, dict)]
    cands += [result or {}]
    for c in cands:
        d = c.get("device")
        if d and d != "?":
            return d
    return None


def session_ceiling(artifact: dict):
    """The session's HBM ceiling: max of the roofline leg's best round
    and every pre-leg health probe recorded in ``probe_history``
    (shared semantics: bench.measured_ceiling)."""
    extras = artifact.get("extras") or {}
    return bench.measured_ceiling(extras.get("roofline_probe") or {},
                                  extras.get("probe_history"))


def micro_done(artifact: dict, leg: str) -> bool:
    r = ((artifact.get("extras") or {}).get("micro") or {}).get(leg)
    return isinstance(r, dict) and "error" not in r


def micro_exhausted(artifact: dict, leg: str) -> bool:
    """Same MAX_ATTEMPTS bound as ``leg_exhausted``: a deterministically
    failing micro leg (e.g. a compile that never fits MICRO_BUDGET) must
    not re-enter ``todo`` on every watcher tick forever — after the cap
    it keeps its recorded error and the prepass moves on."""
    r = ((artifact.get("extras") or {}).get("micro") or {}).get(leg)
    return (isinstance(r, dict) and "error" in r
            and r.get("attempts", 1) >= MAX_ATTEMPTS)


def micro_prepass(artifact: dict, path: Path, legs, params) -> int:
    """Bank a coarse number for EVERY leg before any full budget runs:
    one ``bench.py --leg X --micro`` subprocess per leg (1 round,
    smallest meaningful shape, ~15 s of measurement each), back-to-back
    inside one health window, merged under ``extras.micro`` and
    COMMITTED before the full-budget passes start — a short healthy
    tunnel window leaves a number for all legs instead of one or two
    full ones (r03–r05 each lost most legs to mid-session wedges).

    Returns 0 (prepass complete / nothing to do) or 3 (tunnel wedged —
    whatever was banked is already committed; the watcher retries)."""
    todo = [l for l in legs if not micro_done(artifact, l)
            and not leg_done(artifact, l)
            and not micro_exhausted(artifact, l)]
    if not todo:
        return 0
    healthy, probe_gbs = tunnel_healthy()
    if not healthy:
        print("measure_session: tunnel unhealthy before micro prepass; "
              "stopping (watcher will retry)", flush=True)
        return 3
    if probe_gbs:
        artifact.setdefault("extras", {}).setdefault(
            "probe_history", []).append(
            {"hbm_gbs": probe_gbs, "before_leg": "micro_prepass",
             "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())})
    print(f"measure_session: micro prepass, todo = {todo}", flush=True)
    wedged = False
    for leg in todo:
        t0 = time.perf_counter()
        result = bench._spawn_leg(leg, params, timeout=MICRO_BUDGET,
                                  micro=True)
        result["leg_seconds"] = round(time.perf_counter() - t0, 1)
        micros = artifact.setdefault("extras", {}).setdefault("micro", {})
        if "error" in result:
            prev = micros.get(leg)
            if isinstance(prev, dict) and "error" in prev:
                result["attempts"] = prev.get("attempts", 1) + 1
        micros[leg] = result
        path.write_text(json.dumps(artifact, indent=1) + "\n")
        ok = "error" not in result
        print(f"measure_session: micro {leg} "
              f"{'OK' if ok else 'ERROR'} ({result['leg_seconds']}s): "
              f"{json.dumps(result)[:160]}", flush=True)
        if not ok and "timed out" in str(result.get("error", "")):
            wedged = True
            wedged_leg, wedged_result = leg, result
            break
    n = sum(micro_done(artifact, l) for l in legs)
    commit(path, f"Bench artifact: micro prepass "
                 f"({n}/{len(legs)} legs banked)")
    if wedged:
        print("measure_session: micro leg timeout -> assuming wedge; "
              "stopping", flush=True)
        dump_wedge_bundle(wedged_leg, wedged_result, MICRO_BUDGET)
        return 3
    return 0


def run_leg_with_retry(leg: str, params: dict, budget: int) -> dict:
    """One full-budget attempt; on TIMEOUT, one reduced retry before the
    failure is recorded.  A leg timeout usually means the tunnel wedged,
    but a live-but-slow tunnel can also push a leg past its budget — so
    a timed-out leg re-runs ONCE at a reduced round budget (half the
    measured ``new_tokens`` per round), stamped ``retried_reduced: true``
    so the artifact shows the number came from the reduced shape.  Only
    if the retry also fails does the leg record its error (and the wedge
    path fires on a retry timeout)."""
    t0 = time.perf_counter()
    result = bench._spawn_leg(leg, params, timeout=budget)
    result["leg_seconds"] = round(time.perf_counter() - t0, 1)
    if "timed out" not in str(result.get("error", "")):
        return result
    reduced = dict(params, new_tokens=max(
        16, int(params.get("new_tokens", 128)) // 2))
    print(f"measure_session: {leg} timed out after {budget}s; retrying "
          f"once at reduced round budget "
          f"(new_tokens={reduced['new_tokens']})", flush=True)
    t0 = time.perf_counter()
    retry = bench._spawn_leg(leg, reduced, timeout=budget)
    retry["leg_seconds"] = round(time.perf_counter() - t0, 1)
    retry["retried_reduced"] = True
    return retry


def dump_wedge_bundle(leg: str, result: dict, budget: float) -> None:
    """A bench-leg timeout IS an incident: dump a postmortem bundle
    (flight ring, metrics snapshot, recent SLO timelines — see
    telemetry/postmortem.py) so the wedge window is diagnosable after
    the watcher moves on.  Best-effort: the bundle must never turn a
    timeout exit into a crash exit.  ``DWT_POSTMORTEM_DIR`` wins when
    set; otherwise bundles land under ``postmortems/`` in the repo."""
    try:
        from distributed_inference_demo_tpu.telemetry.postmortem import (
            PostmortemWriter)
        out_dir = os.environ.get("DWT_POSTMORTEM_DIR") or str(
            REPO / "postmortems")
        writer = PostmortemWriter(out_dir, proc="measure_session")
        bundle = writer.write_bundle(
            "bench_leg_timeout",
            detail={"leg": leg, "budget_s": budget,
                    "error": str(result.get("error", ""))[:512],
                    "leg_seconds": result.get("leg_seconds")})
        if bundle:
            print(f"measure_session: wedge postmortem bundle at "
                  f"{bundle}", flush=True)
    except Exception as e:
        print(f"measure_session: postmortem bundle failed: {e}",
              flush=True)


def commit(path: Path, msg: str) -> bool:
    """Path-scoped add+commit of the artifact AND the roofline ledger
    (the declared ceiling must travel with the numbers judged against
    it); a FAILED commit is loudly visible in the watcher log (a silent
    failure would quietly drop the 'artifact durable after every leg'
    guarantee this harness exists for — e.g. index.lock contention with
    a concurrent watcher)."""
    paths = [str(path)]
    if bench.ROOFLINE_LEDGER_PATH.exists():
        paths.append(str(bench.ROOFLINE_LEDGER_PATH))
    for cmd in (["git", "add"] + paths,
                ["git", "commit", "-m", msg, "--"] + paths):
        p = subprocess.run(cmd, cwd=str(REPO), stdout=subprocess.DEVNULL,
                           stderr=subprocess.PIPE, text=True)
        if p.returncode != 0:
            print(f"measure_session: WARNING: artifact NOT committed "
                  f"({' '.join(cmd[:2])} rc={p.returncode}: "
                  f"{(p.stderr or '').strip()[:200]})", flush=True)
            return False
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--artifact", default=bench.PRIOR_ARTIFACT_NAME)
    ap.add_argument("--legs", default=",".join(DEFAULT_LEGS))
    ap.add_argument("--force", default="",
                    help="comma list of legs to re-run even if done")
    ap.add_argument("--no-micro", action="store_true",
                    help="skip the micro prepass (full-budget legs only)")
    args = ap.parse_args()

    path = REPO / args.artifact
    legs = [l for l in args.legs.split(",") if l]
    force = set(args.force.split(",")) - {""}
    params = {
        "model": os.environ.get("BENCH_MODEL", "tinyllama-1.1b"),
        "batch": int(os.environ.get("BENCH_BATCH", "8")),
        "prompt_len": int(os.environ.get("BENCH_PROMPT", "64")),
        "new_tokens": int(os.environ.get("BENCH_NEW_TOKENS", "128")),
        "flagship": os.environ.get("BENCH_FLAGSHIP", "llama-3-8b"),
    }

    artifact = load_artifact(path)
    if not args.no_micro:
        rc = micro_prepass(artifact, path, legs, params)
        if rc:
            return rc           # banked micros are already committed
    todo = [l for l in legs if l in force
            or (not leg_done(artifact, l)
                and not leg_exhausted(artifact, l))]
    if not todo:
        done = sum(leg_done(artifact, l) for l in legs)
        print(f"measure_session: all legs done or exhausted "
              f"({done}/{len(legs)} measured)")
        return 0
    print(f"measure_session: todo = {todo}", flush=True)

    for leg in todo:
        healthy, probe_gbs = tunnel_healthy()
        if not healthy:
            print(f"measure_session: tunnel unhealthy before {leg}; "
                  "stopping (watcher will retry)", flush=True)
            return 3
        if probe_gbs:
            # bracket probe: persisted with the leg's merge below, so the
            # ceiling reflects tunnel health AROUND each measurement
            artifact.setdefault("extras", {}).setdefault(
                "probe_history", []).append(
                {"hbm_gbs": probe_gbs, "before_leg": leg,
                 "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())})
        budget = LEG_BUDGETS.get(leg, 1500)
        result = run_leg_with_retry(leg, params, budget)
        dt = result["leg_seconds"]
        # legs land across hours as the tunnel allows, possibly spanning
        # perf commits — stamp each with the code it actually measured
        head = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                              cwd=str(REPO), capture_output=True,
                              text=True).stdout.strip()
        if head:
            result["git_head"] = head
        artifact = merge(artifact, leg, result, params)
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        # append session provenance without destroying the hand-written
        # history already in the note
        note = artifact.get("note", "")
        marker = " [incremental session:"
        base_note = note.split(marker)[0]
        artifact["note"] = (
            f"{base_note}{marker} legs re-run one per subprocess via "
            f"tools/measure_session.py; last leg {leg} at {stamp}]")
        path.write_text(json.dumps(artifact, indent=1) + "\n")
        ok = "error" not in result
        print(f"measure_session: {leg} {'OK' if ok else 'ERROR'} "
              f"({dt}s): {json.dumps(result)[:200]}", flush=True)
        commit(path, f"Bench artifact: {leg} leg "
                     f"({'measured' if ok else 'errored'}, incremental "
                     "session)")
        if not ok and "timed out" in str(result.get("error", "")):
            # a timeout usually means the tunnel wedged mid-leg: stop and
            # let the watcher re-probe rather than burning every budget
            print("measure_session: leg timeout -> assuming wedge; "
                  "stopping", flush=True)
            dump_wedge_bundle(leg, result, budget)
            return 3
    artifact = load_artifact(path)
    remaining = [l for l in legs if not leg_done(artifact, l)
                 and not leg_exhausted(artifact, l)]
    if remaining:
        # some attempted legs errored (non-timeout) and still have retry
        # budget: NOT done — the watcher must come back for them
        print(f"measure_session: attempted all; still unmeasured "
              f"(will retry): {remaining}", flush=True)
        return 2
    print("measure_session: session complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
