"""Incremental TPU measurement session: one bench leg per subprocess,
merged into the round's self-artifact and committed AFTER EACH LEG.

Why not one monolithic ``python bench.py`` run: the axon tunnel wedges
mid-session (r04's first full run lost 6 legs to a wedge that began
~15 minutes in; r03 lost its entire driver bench the same way).  This
harness makes every completed leg durable immediately:

  for each leg missing-or-errored in the artifact:
      1. health-probe the tunnel with REAL compute (a small matmul --
         ``jax.devices()`` answers even when dispatch is wedged)
      2. run ``bench.py --leg <name>`` in a subprocess with its own budget
      3. merge the result into the artifact, recompute derived fields,
         git-commit the artifact (path-scoped)
      4. a failed health probe ends the session; the next invocation
         (tools/tpu_watch.sh loops on this) resumes at the first missing leg

Usage: ``python tools/measure_session.py [--artifact BENCH_SELF_r04.json]
[--legs a,b,c] [--once-healthy-seconds N]``
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# leg -> subprocess budget (s).  Generous: a leg is only attempted when
# the tunnel just answered a compute probe, and a hung leg ends the
# session anyway (the watcher retries later).
LEG_BUDGETS = {
    "roofline_probe": 600,
    "headline": 1200,
    "headline_int8": 1200,
    "speculative": 1500,
    "prompt_lookup": 1500,
    "planner_pipeline": 1800,
    "long_context": 1800,
    "flagship_int8": 2400,
    "batching": 2400,
    "sweep": 1800,
    "flagship_bf16": 2400,
    "pipeline": 1500,
    "prefill_long": 1800,
}
DEFAULT_LEGS = list(LEG_BUDGETS)


def sh(cmd, timeout):
    """Run, returning (rc_or_None, stdout).  SIGKILLs the group on
    timeout (a wedged-tunnel process ignores SIGTERM)."""
    p = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                         stderr=subprocess.DEVNULL, text=True,
                         start_new_session=True, cwd=str(REPO))
    try:
        out, _ = p.communicate(timeout=timeout)
        return p.returncode, out
    except subprocess.TimeoutExpired:
        try:
            import signal
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        except OSError:
            pass
        p.wait()
        return None, ""


def tunnel_healthy(timeout=240) -> bool:
    """A REAL dispatch probe: 1k matmul + block_until_ready."""
    rc, _ = sh([sys.executable, "-c",
                "import jax, jax.numpy as jnp;"
                "x = jnp.ones((1024, 1024), jnp.bfloat16);"
                "(x @ x).block_until_ready(); print('ok')"], timeout)
    return rc == 0


def load_artifact(path: Path) -> dict:
    if path.exists():
        return json.loads(path.read_text())
    return {"note": "", "metric": None, "value": None,
            "unit": "tokens/sec", "vs_baseline": None,
            "headline": {}, "extras": {}}


def leg_result(artifact: dict, leg: str):
    if leg == "headline":
        return artifact.get("headline") or None
    return (artifact.get("extras") or {}).get(leg)


def leg_done(artifact: dict, leg: str) -> bool:
    r = leg_result(artifact, leg)
    return isinstance(r, dict) and bool(r) and "error" not in r


def merge(artifact: dict, leg: str, result: dict, params: dict) -> dict:
    if leg == "headline":
        artifact["headline"] = result
        tps = result.get("decode_tokens_per_sec")
        artifact["value"] = tps
        artifact["metric"] = (
            f"decode tokens/sec ({params['model']}, "
            f"{result.get('dtype', '?')}, batch={params['batch']}, "
            f"prompt={params['prompt_len']}, new={params['new_tokens']}, "
            f"device={result.get('device', '?')}) vs measured 2-process "
            "CPU socket-pipeline baseline")
        base = json.loads((REPO / "tools" / "cpu_baseline.json").read_text())
        bt = base.get("tokens_per_sec")
        comparable = all(base.get(k) == params[k] for k in
                         ("model", "batch", "prompt_len", "new_tokens"))
        artifact["vs_baseline"] = (round(tps / bt, 2)
                                   if tps and bt and comparable else None)
        artifact.setdefault("extras", {})["baseline"] = {
            k: base.get(k) for k in
            ("tokens_per_sec", "model", "dtype", "batch", "host", "cpu",
             "measured_at", "source")}
    else:
        artifact.setdefault("extras", {})[leg] = result

    # measured-ceiling fractions: this SESSION's probe if present, else
    # keep whatever the leg computed against the paper number
    measured = (artifact.get("extras", {})
                .get("roofline_probe", {}) or {}).get("hbm_read_gbs")
    if measured:
        def add_measured(r):
            if isinstance(r, dict) and r.get("achieved_gbs"):
                r["hbm_roofline_frac_measured"] = round(
                    r["achieved_gbs"] / measured, 3)
        add_measured(artifact.get("headline", {}))
        for key in ("headline_int8", "flagship_int8", "flagship_bf16"):
            add_measured(artifact["extras"].get(key, {}))
        for pt in (artifact["extras"].get("sweep", {}) or {}).get(
                "points", []):
            add_measured(pt)
    return artifact


def commit(path: Path, msg: str):
    subprocess.run(["git", "add", str(path)], cwd=str(REPO))
    subprocess.run(["git", "commit", "-m", msg, "--", str(path)],
                   cwd=str(REPO), stdout=subprocess.DEVNULL)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--artifact", default="BENCH_SELF_r04.json")
    ap.add_argument("--legs", default=",".join(DEFAULT_LEGS))
    ap.add_argument("--force", default="",
                    help="comma list of legs to re-run even if done")
    args = ap.parse_args()

    path = REPO / args.artifact
    legs = [l for l in args.legs.split(",") if l]
    force = set(args.force.split(",")) - {""}
    params = {
        "model": os.environ.get("BENCH_MODEL", "tinyllama-1.1b"),
        "batch": int(os.environ.get("BENCH_BATCH", "8")),
        "prompt_len": int(os.environ.get("BENCH_PROMPT", "64")),
        "new_tokens": int(os.environ.get("BENCH_NEW_TOKENS", "128")),
        "flagship": os.environ.get("BENCH_FLAGSHIP", "llama-3-8b"),
    }

    artifact = load_artifact(path)
    todo = [l for l in legs if l in force or not leg_done(artifact, l)]
    if not todo:
        print("measure_session: all legs done")
        return 0
    print(f"measure_session: todo = {todo}", flush=True)

    for leg in todo:
        if not tunnel_healthy():
            print(f"measure_session: tunnel unhealthy before {leg}; "
                  "stopping (watcher will retry)", flush=True)
            return 3
        budget = LEG_BUDGETS.get(leg, 1500)
        t0 = time.perf_counter()
        rc, out = sh([sys.executable, str(REPO / "bench.py"), "--leg", leg,
                      "--params", json.dumps(params)], budget)
        dt = round(time.perf_counter() - t0, 1)
        if rc == 0 and out.strip():
            try:
                result = json.loads(out.strip().splitlines()[-1])
            except json.JSONDecodeError:
                result = {"error": f"unparseable leg output: {out[-300:]}"}
        elif rc is None:
            result = {"error": f"leg timed out after {budget}s "
                               "(incremental session)"}
        else:
            result = {"error": f"leg exited rc={rc}"}
        result["leg_seconds"] = dt
        artifact = merge(artifact, leg, result, params)
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        artifact["note"] = (
            "Self-measured incrementally on the axon-tunneled single TPU "
            "v5 lite (tools/measure_session.py): legs run one per "
            "subprocess and committed as they land, because the tunnel "
            f"wedges mid-session. Last leg: {leg} at {stamp}.")
        path.write_text(json.dumps(artifact, indent=1) + "\n")
        ok = "error" not in result
        print(f"measure_session: {leg} {'OK' if ok else 'ERROR'} "
              f"({dt}s): {json.dumps(result)[:200]}", flush=True)
        commit(path, f"Bench artifact: {leg} leg "
                     f"({'measured' if ok else 'errored'}, incremental "
                     "session)")
        if not ok and "timed out" in str(result.get("error", "")):
            # a timeout usually means the tunnel wedged mid-leg: stop and
            # let the watcher re-probe rather than burning every budget
            print("measure_session: leg timeout -> assuming wedge; "
                  "stopping", flush=True)
            return 3
    print("measure_session: session complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
