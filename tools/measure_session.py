"""Incremental TPU measurement session: one bench leg per subprocess,
merged into the round's self-artifact and committed AFTER EACH LEG.

Why not one monolithic ``python bench.py`` run: the axon tunnel wedges
mid-session (r04's first full run lost 6 legs to a wedge that began
~15 minutes in; r03 lost its entire driver bench the same way).  This
harness makes every completed leg durable immediately:

  for each leg missing-or-errored in the artifact:
      1. health-probe the tunnel with REAL compute (a small matmul --
         ``jax.devices()`` answers even when dispatch is wedged)
      2. run ``bench.py --leg <name>`` in a subprocess with its own budget
         (bench's group-killable spawner: stderr tail on failure, survives
         D-state children)
      3. merge the result into the artifact, recompute derived fields,
         git-commit the artifact (path-scoped)
      4. a failed health probe ends the session; the next invocation
         (tools/tpu_watch.sh loops on this) resumes at the first missing leg

Usage: ``python tools/measure_session.py [--artifact BENCH_SELF_r04.json]
[--legs a,b,c] [--force a,b]``
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402  (leg spawner + group-killable runner)

# leg -> subprocess budget (s).  Generous: a leg is only attempted when
# the tunnel just answered a compute probe, and a hung leg ends the
# session anyway (the watcher retries later).
LEG_BUDGETS = {
    "roofline_probe": 600,
    "headline": 1200,
    "headline_int8": 1200,
    "speculative": 1500,
    "prompt_lookup": 1500,
    "planner_pipeline": 1800,
    "long_context": 1800,
    "flagship_int8": 2400,
    "batching": 2400,
    "prefix_reuse": 1800,
    "paged_decode": 1800,
    "sweep": 1800,
    "flagship_bf16": 2400,
    "pipeline": 1500,
    "prefill_long": 1800,
    "moe": 1800,
    "multimodal": 1500,
    "int4": 2400,
}
DEFAULT_LEGS = list(LEG_BUDGETS)


_PROBE_SRC = """
import time, jax, jax.numpy as jnp
x = jnp.ones((1024, 1024), jnp.bfloat16)
(x @ x).block_until_ready()
big = jnp.ones((1 << 29,), jnp.bfloat16)   # 1 GiB

def red(v):
    # each iteration mixes the scan input into the read so the reduce is
    # NOT loop-invariant (XLA LICM could hoist an invariant sum and the
    # probe would divide 1 GiB of real traffic by 16 GiB)
    def rep(acc, x):
        return acc + jnp.sum((v + x).astype(jnp.float32)), None
    return jax.lax.scan(rep, 0.0, jnp.arange(16, dtype=v.dtype))[0]

f = jax.jit(red)
float(f(big))
t0 = time.perf_counter()
float(f(big))
dt = time.perf_counter() - t0
print('hbm_gbs=%.1f' % (big.nbytes * 16 / dt / 1e9))
print('platform=' + jax.devices()[0].platform)
"""


def tunnel_healthy(timeout=240):
    """A REAL dispatch probe: 1k matmul + block_until_ready, AND the
    platform must actually be a TPU — if the tunnel drops and jax falls
    back to CPU, the matmul succeeds in milliseconds and every leg would
    happily commit CPU-speed numbers over the TPU measurements.

    Also times a 16 GiB HBM read so the session accumulates a bandwidth
    bracket AROUND every leg (leg N's post-probe is leg N+1's pre-probe).
    The r04 artifact's headline beat its own 'measured ceiling' because
    the one roofline probe ran while the tunnel was degrading; the
    ceiling is now the MAX over all session probes.  Returns
    ``(healthy, hbm_gbs_or_None)``."""
    rc, out, _ = bench._run_group_killable(
        [sys.executable, "-c", _PROBE_SRC], timeout)
    ok = rc == 0 and "platform=tpu" in (out or "")
    gbs = None
    for line in (out or "").splitlines():
        if line.startswith("hbm_gbs="):
            try:
                gbs = float(line.split("=", 1)[1])
            except ValueError:
                pass
    return ok, gbs


def load_artifact(path: Path) -> dict:
    if path.exists():
        return json.loads(path.read_text())
    return {"note": "", "metric": None, "value": None,
            "unit": "tokens/sec", "vs_baseline": None,
            "headline": {}, "extras": {}}


def leg_result(artifact: dict, leg: str):
    if leg == "headline":
        return artifact.get("headline") or None
    return (artifact.get("extras") or {}).get(leg)


def leg_done(artifact: dict, leg: str) -> bool:
    r = leg_result(artifact, leg)
    return isinstance(r, dict) and bool(r) and "error" not in r


MAX_ATTEMPTS = 3


def leg_exhausted(artifact: dict, leg: str) -> bool:
    """An errored leg is retried up to MAX_ATTEMPTS times (transient
    tunnel faults), then left as its recorded error — without this bound
    a deterministic failure would keep the watcher re-running an
    expensive leg (and committing) every tick, forever."""
    r = leg_result(artifact, leg)
    if leg == "headline":
        # headline errors are recorded aside (never clobber the measured
        # top-level value), so the attempt count lives there
        r = (artifact.get("extras") or {}).get("headline_rerun")
    return (isinstance(r, dict) and "error" in r
            and r.get("attempts", 1) >= MAX_ATTEMPTS)


def merge(artifact: dict, leg: str, result: dict, params: dict) -> dict:
    if "error" in result and leg_done(artifact, leg):
        # never clobber a measured result with an error dict (a --force
        # re-run that hit a wedge would otherwise destroy data in git);
        # record the failed attempt alongside — carrying the attempts
        # counter so repeatedly-failing forced re-runs register in the
        # retry ledger like any other errored leg
        prev = (artifact.get("extras") or {}).get(f"{leg}_rerun")
        if isinstance(prev, dict) and "error" in prev:
            result["attempts"] = prev.get("attempts", 1) + 1
        artifact.setdefault("extras", {})[f"{leg}_rerun"] = result
        return artifact
    if leg == "headline":
        if "error" in result:
            prev = (artifact.get("extras") or {}).get("headline_rerun")
            if isinstance(prev, dict) and "error" in prev:
                result["attempts"] = prev.get("attempts", 1) + 1
            artifact.setdefault("extras", {})["headline_rerun"] = result
            return artifact
        artifact["headline"] = result
        # one owner for the metric string / comparability caveats:
        # bench.headline_summary (shared with bench.py main())
        summary = bench.headline_summary(result, params,
                                         result.get("device", "?"))
        artifact["metric"] = summary["metric"]
        artifact["value"] = summary["value"]
        artifact["vs_baseline"] = summary["vs_baseline"]
        artifact.setdefault("extras", {})["baseline"] = summary["baseline"]
    else:
        prev = (artifact.get("extras") or {}).get(leg)
        if "error" in result and isinstance(prev, dict) and "error" in prev:
            result["attempts"] = prev.get("attempts", 1) + 1
        artifact.setdefault("extras", {})[leg] = result

    # measured-ceiling fractions: the MAX over the roofline leg and every
    # per-leg health probe this session (the probes bracket each leg, so
    # a ceiling measured during tunnel degradation can't stay the
    # ceiling).  If a decode leg still beats the max probe, that is
    # labeled rather than silently reported as frac > 1.
    measured = session_ceiling(artifact)
    if measured:
        artifact.setdefault("extras", {})["measured_ceiling_gbs"] = measured
        bench.apply_measured_frac(artifact.get("headline", {}), measured)
        for key in ("headline_int8", "flagship_int8", "flagship_bf16"):
            bench.apply_measured_frac(artifact["extras"].get(key, {}),
                                      measured)
        for pt in (artifact["extras"].get("sweep", {}) or {}).get(
                "points", []):
            bench.apply_measured_frac(pt, measured)
        for sub in (artifact["extras"].get("int4", {}) or {}).values():
            bench.apply_measured_frac(sub, measured)
    return artifact


def session_ceiling(artifact: dict):
    """The session's HBM ceiling: max of the roofline leg's best round
    and every pre-leg health probe recorded in ``probe_history``
    (shared semantics: bench.measured_ceiling)."""
    extras = artifact.get("extras") or {}
    return bench.measured_ceiling(extras.get("roofline_probe") or {},
                                  extras.get("probe_history"))


def commit(path: Path, msg: str) -> bool:
    """Path-scoped add+commit; a FAILED commit is loudly visible in the
    watcher log (a silent failure would quietly drop the
    'artifact durable after every leg' guarantee this harness exists
    for — e.g. index.lock contention with a concurrent watcher)."""
    for cmd in (["git", "add", str(path)],
                ["git", "commit", "-m", msg, "--", str(path)]):
        p = subprocess.run(cmd, cwd=str(REPO), stdout=subprocess.DEVNULL,
                           stderr=subprocess.PIPE, text=True)
        if p.returncode != 0:
            print(f"measure_session: WARNING: artifact NOT committed "
                  f"({' '.join(cmd[:2])} rc={p.returncode}: "
                  f"{(p.stderr or '').strip()[:200]})", flush=True)
            return False
    return True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--artifact", default=bench.PRIOR_ARTIFACT_NAME)
    ap.add_argument("--legs", default=",".join(DEFAULT_LEGS))
    ap.add_argument("--force", default="",
                    help="comma list of legs to re-run even if done")
    args = ap.parse_args()

    path = REPO / args.artifact
    legs = [l for l in args.legs.split(",") if l]
    force = set(args.force.split(",")) - {""}
    params = {
        "model": os.environ.get("BENCH_MODEL", "tinyllama-1.1b"),
        "batch": int(os.environ.get("BENCH_BATCH", "8")),
        "prompt_len": int(os.environ.get("BENCH_PROMPT", "64")),
        "new_tokens": int(os.environ.get("BENCH_NEW_TOKENS", "128")),
        "flagship": os.environ.get("BENCH_FLAGSHIP", "llama-3-8b"),
    }

    artifact = load_artifact(path)
    todo = [l for l in legs if l in force
            or (not leg_done(artifact, l)
                and not leg_exhausted(artifact, l))]
    if not todo:
        done = sum(leg_done(artifact, l) for l in legs)
        print(f"measure_session: all legs done or exhausted "
              f"({done}/{len(legs)} measured)")
        return 0
    print(f"measure_session: todo = {todo}", flush=True)

    for leg in todo:
        healthy, probe_gbs = tunnel_healthy()
        if not healthy:
            print(f"measure_session: tunnel unhealthy before {leg}; "
                  "stopping (watcher will retry)", flush=True)
            return 3
        if probe_gbs:
            # bracket probe: persisted with the leg's merge below, so the
            # ceiling reflects tunnel health AROUND each measurement
            artifact.setdefault("extras", {}).setdefault(
                "probe_history", []).append(
                {"hbm_gbs": probe_gbs, "before_leg": leg,
                 "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())})
        budget = LEG_BUDGETS.get(leg, 1500)
        t0 = time.perf_counter()
        result = bench._spawn_leg(leg, params, timeout=budget)
        dt = round(time.perf_counter() - t0, 1)
        result["leg_seconds"] = dt
        # legs land across hours as the tunnel allows, possibly spanning
        # perf commits — stamp each with the code it actually measured
        head = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                              cwd=str(REPO), capture_output=True,
                              text=True).stdout.strip()
        if head:
            result["git_head"] = head
        artifact = merge(artifact, leg, result, params)
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        # append session provenance without destroying the hand-written
        # history already in the note
        note = artifact.get("note", "")
        marker = " [incremental session:"
        base_note = note.split(marker)[0]
        artifact["note"] = (
            f"{base_note}{marker} legs re-run one per subprocess via "
            f"tools/measure_session.py; last leg {leg} at {stamp}]")
        path.write_text(json.dumps(artifact, indent=1) + "\n")
        ok = "error" not in result
        print(f"measure_session: {leg} {'OK' if ok else 'ERROR'} "
              f"({dt}s): {json.dumps(result)[:200]}", flush=True)
        commit(path, f"Bench artifact: {leg} leg "
                     f"({'measured' if ok else 'errored'}, incremental "
                     "session)")
        if not ok and "timed out" in str(result.get("error", "")):
            # a timeout usually means the tunnel wedged mid-leg: stop and
            # let the watcher re-probe rather than burning every budget
            print("measure_session: leg timeout -> assuming wedge; "
                  "stopping", flush=True)
            return 3
    artifact = load_artifact(path)
    remaining = [l for l in legs if not leg_done(artifact, l)
                 and not leg_exhausted(artifact, l)]
    if remaining:
        # some attempted legs errored (non-timeout) and still have retry
        # budget: NOT done — the watcher must come back for them
        print(f"measure_session: attempted all; still unmeasured "
              f"(will retry): {remaining}", flush=True)
        return 2
    print("measure_session: session complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
