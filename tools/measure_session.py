"""Incremental TPU measurement session: one bench leg per subprocess,
merged into the round's self-artifact and committed AFTER EACH LEG.

Why not one monolithic ``python bench.py`` run: the axon tunnel wedges
mid-session (r04's first full run lost 6 legs to a wedge that began
~15 minutes in; r03 lost its entire driver bench the same way).  This
harness makes every completed leg durable immediately:

  for each leg missing-or-errored in the artifact:
      1. health-probe the tunnel with REAL compute (a small matmul --
         ``jax.devices()`` answers even when dispatch is wedged)
      2. run ``bench.py --leg <name>`` in a subprocess with its own budget
         (bench's group-killable spawner: stderr tail on failure, survives
         D-state children)
      3. merge the result into the artifact, recompute derived fields,
         git-commit the artifact (path-scoped)
      4. a failed health probe ends the session; the next invocation
         (tools/tpu_watch.sh loops on this) resumes at the first missing leg

Usage: ``python tools/measure_session.py [--artifact BENCH_SELF_r04.json]
[--legs a,b,c] [--force a,b]``
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

import bench  # noqa: E402  (leg spawner + group-killable runner)

# leg -> subprocess budget (s).  Generous: a leg is only attempted when
# the tunnel just answered a compute probe, and a hung leg ends the
# session anyway (the watcher retries later).
LEG_BUDGETS = {
    "roofline_probe": 600,
    "headline": 1200,
    "headline_int8": 1200,
    "speculative": 1500,
    "prompt_lookup": 1500,
    "planner_pipeline": 1800,
    "long_context": 1800,
    "flagship_int8": 2400,
    "batching": 2400,
    "sweep": 1800,
    "flagship_bf16": 2400,
    "pipeline": 1500,
    "prefill_long": 1800,
}
DEFAULT_LEGS = list(LEG_BUDGETS)


def tunnel_healthy(timeout=240) -> bool:
    """A REAL dispatch probe: 1k matmul + block_until_ready, AND the
    platform must actually be a TPU — if the tunnel drops and jax falls
    back to CPU, the matmul succeeds in milliseconds and every leg would
    happily commit CPU-speed numbers over the TPU measurements."""
    rc, out, _ = bench._run_group_killable(
        [sys.executable, "-c",
         "import jax, jax.numpy as jnp;"
         "x = jnp.ones((1024, 1024), jnp.bfloat16);"
         "(x @ x).block_until_ready();"
         "print('platform=' + jax.devices()[0].platform)"], timeout)
    return rc == 0 and "platform=tpu" in (out or "")


def load_artifact(path: Path) -> dict:
    if path.exists():
        return json.loads(path.read_text())
    return {"note": "", "metric": None, "value": None,
            "unit": "tokens/sec", "vs_baseline": None,
            "headline": {}, "extras": {}}


def leg_result(artifact: dict, leg: str):
    if leg == "headline":
        return artifact.get("headline") or None
    return (artifact.get("extras") or {}).get(leg)


def leg_done(artifact: dict, leg: str) -> bool:
    r = leg_result(artifact, leg)
    return isinstance(r, dict) and bool(r) and "error" not in r


MAX_ATTEMPTS = 3


def leg_exhausted(artifact: dict, leg: str) -> bool:
    """An errored leg is retried up to MAX_ATTEMPTS times (transient
    tunnel faults), then left as its recorded error — without this bound
    a deterministic failure would keep the watcher re-running an
    expensive leg (and committing) every tick, forever."""
    r = leg_result(artifact, leg)
    if leg == "headline":
        # headline errors are recorded aside (never clobber the measured
        # top-level value), so the attempt count lives there
        r = (artifact.get("extras") or {}).get("headline_rerun")
    return (isinstance(r, dict) and "error" in r
            and r.get("attempts", 1) >= MAX_ATTEMPTS)


def merge(artifact: dict, leg: str, result: dict, params: dict) -> dict:
    if "error" in result and leg_done(artifact, leg):
        # never clobber a measured result with an error dict (a --force
        # re-run that hit a wedge would otherwise destroy data in git);
        # record the failed attempt alongside
        artifact.setdefault("extras", {})[f"{leg}_rerun"] = result
        return artifact
    if leg == "headline":
        if "error" in result:
            prev = (artifact.get("extras") or {}).get("headline_rerun")
            if isinstance(prev, dict) and "error" in prev:
                result["attempts"] = prev.get("attempts", 1) + 1
            artifact.setdefault("extras", {})["headline_rerun"] = result
            return artifact
        artifact["headline"] = result
        # one owner for the metric string / comparability caveats:
        # bench.headline_summary (shared with bench.py main())
        summary = bench.headline_summary(result, params,
                                         result.get("device", "?"))
        artifact["metric"] = summary["metric"]
        artifact["value"] = summary["value"]
        artifact["vs_baseline"] = summary["vs_baseline"]
        artifact.setdefault("extras", {})["baseline"] = summary["baseline"]
    else:
        prev = (artifact.get("extras") or {}).get(leg)
        if "error" in result and isinstance(prev, dict) and "error" in prev:
            result["attempts"] = prev.get("attempts", 1) + 1
        artifact.setdefault("extras", {})[leg] = result

    # measured-ceiling fractions: this SESSION's probe if present, else
    # keep whatever the leg computed against the paper number
    measured = (artifact.get("extras", {})
                .get("roofline_probe", {}) or {}).get("hbm_read_gbs")
    if measured:
        def add_measured(r):
            if isinstance(r, dict) and r.get("achieved_gbs"):
                r["hbm_roofline_frac_measured"] = round(
                    r["achieved_gbs"] / measured, 3)
        add_measured(artifact.get("headline", {}))
        for key in ("headline_int8", "flagship_int8", "flagship_bf16"):
            add_measured(artifact["extras"].get(key, {}))
        for pt in (artifact["extras"].get("sweep", {}) or {}).get(
                "points", []):
            add_measured(pt)
    return artifact


def commit(path: Path, msg: str):
    subprocess.run(["git", "add", str(path)], cwd=str(REPO))
    subprocess.run(["git", "commit", "-m", msg, "--", str(path)],
                   cwd=str(REPO), stdout=subprocess.DEVNULL)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--artifact", default=bench.PRIOR_ARTIFACT_NAME)
    ap.add_argument("--legs", default=",".join(DEFAULT_LEGS))
    ap.add_argument("--force", default="",
                    help="comma list of legs to re-run even if done")
    args = ap.parse_args()

    path = REPO / args.artifact
    legs = [l for l in args.legs.split(",") if l]
    force = set(args.force.split(",")) - {""}
    params = {
        "model": os.environ.get("BENCH_MODEL", "tinyllama-1.1b"),
        "batch": int(os.environ.get("BENCH_BATCH", "8")),
        "prompt_len": int(os.environ.get("BENCH_PROMPT", "64")),
        "new_tokens": int(os.environ.get("BENCH_NEW_TOKENS", "128")),
        "flagship": os.environ.get("BENCH_FLAGSHIP", "llama-3-8b"),
    }

    artifact = load_artifact(path)
    todo = [l for l in legs if l in force
            or (not leg_done(artifact, l)
                and not leg_exhausted(artifact, l))]
    if not todo:
        done = sum(leg_done(artifact, l) for l in legs)
        print(f"measure_session: all legs done or exhausted "
              f"({done}/{len(legs)} measured)")
        return 0
    print(f"measure_session: todo = {todo}", flush=True)

    for leg in todo:
        if not tunnel_healthy():
            print(f"measure_session: tunnel unhealthy before {leg}; "
                  "stopping (watcher will retry)", flush=True)
            return 3
        budget = LEG_BUDGETS.get(leg, 1500)
        t0 = time.perf_counter()
        result = bench._spawn_leg(leg, params, timeout=budget)
        dt = round(time.perf_counter() - t0, 1)
        result["leg_seconds"] = dt
        # legs land across hours as the tunnel allows, possibly spanning
        # perf commits — stamp each with the code it actually measured
        head = subprocess.run(["git", "rev-parse", "--short", "HEAD"],
                              cwd=str(REPO), capture_output=True,
                              text=True).stdout.strip()
        if head:
            result["git_head"] = head
        artifact = merge(artifact, leg, result, params)
        stamp = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        # append session provenance without destroying the hand-written
        # history already in the note
        note = artifact.get("note", "")
        marker = " [incremental session:"
        base_note = note.split(marker)[0]
        artifact["note"] = (
            f"{base_note}{marker} legs re-run one per subprocess via "
            f"tools/measure_session.py; last leg {leg} at {stamp}]")
        path.write_text(json.dumps(artifact, indent=1) + "\n")
        ok = "error" not in result
        print(f"measure_session: {leg} {'OK' if ok else 'ERROR'} "
              f"({dt}s): {json.dumps(result)[:200]}", flush=True)
        commit(path, f"Bench artifact: {leg} leg "
                     f"({'measured' if ok else 'errored'}, incremental "
                     "session)")
        if not ok and "timed out" in str(result.get("error", "")):
            # a timeout usually means the tunnel wedged mid-leg: stop and
            # let the watcher re-probe rather than burning every budget
            print("measure_session: leg timeout -> assuming wedge; "
                  "stopping", flush=True)
            return 3
    artifact = load_artifact(path)
    remaining = [l for l in legs if not leg_done(artifact, l)
                 and not leg_exhausted(artifact, l)]
    if remaining:
        # some attempted legs errored (non-timeout) and still have retry
        # budget: NOT done — the watcher must come back for them
        print(f"measure_session: attempted all; still unmeasured "
              f"(will retry): {remaining}", flush=True)
        return 2
    print("measure_session: session complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
