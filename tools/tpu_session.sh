#!/bin/bash
# One-shot TPU measurement session for when the axon tunnel is healthy:
#   1. int8 dequant strategy probe   (tools/int8_dequant_probe.py)
#   2. sampling cost probe           (tools/sampling_cost_probe.py)
#   3. full bench                    (bench.py -> /tmp/bench_refresh.json)
# Each step appends to /tmp/tpu_session.log; steps are independent so a
# wedged tunnel mid-way still leaves earlier results on disk.
set -x
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_session.log
: > "$LOG"
echo "=== tunnel check $(date -u +%H:%M:%S) ===" >> "$LOG"
timeout 180 python -c "import jax; print(jax.devices())" >> "$LOG" 2>&1 || {
  echo "TUNNEL DOWN" >> "$LOG"; exit 1; }
echo "=== int8 dequant probe ===" >> "$LOG"
timeout 2400 python tools/int8_dequant_probe.py >> "$LOG" 2>&1
echo "=== sampling cost probe ===" >> "$LOG"
timeout 2400 python tools/sampling_cost_probe.py >> "$LOG" 2>&1
echo "=== full bench ===" >> "$LOG"
rm -f /tmp/bench_refresh.json   # never let a stale run masquerade as this one
if BENCH_DEADLINE_S=3000 timeout 3600 python bench.py > /tmp/bench_refresh.json 2>> "$LOG"; then
  cp /tmp/bench_refresh.json BENCH_TUNNEL_RECOVERY.json
else
  echo "bench.py failed or timed out; no BENCH_TUNNEL_RECOVERY.json" >> "$LOG"
fi
echo "=== done $(date -u +%H:%M:%S) ===" >> "$LOG"
# land the probe log inside the repo so an end-of-round auto-commit
# preserves it even if no interactive session is alive to fold it in
{ echo "# Probe + bench results from the tunnel-recovery watcher."
  echo "# Produced by tools/tpu_session.sh at $(date -u +%FT%TZ)."
  cat "$LOG"; } > TUNNEL_RECOVERY_PROBES.log
