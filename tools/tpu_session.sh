#!/bin/bash
# One-shot TPU measurement session for when the axon tunnel is healthy:
#   1. full bench (ALL legs, generous deadline) -> BENCH_TUNNEL_RECOVERY.json
#   2. decode-profile probe (tools/decode_profile_probe.py, if present)
#   3. int8 dequant strategy probe   (tools/int8_dequant_probe.py)
#   4. sampling cost probe           (tools/sampling_cost_probe.py)
# The bench runs FIRST: it is the round's evidence, and the tunnel can die
# again mid-session — probes are gravy.  Each step appends to
# /tmp/tpu_session.log; steps are independent so a wedged tunnel mid-way
# still leaves earlier results on disk.  Artifacts are COMMITTED (path-
# scoped) so an end-of-round untracked-file finding can't happen again.
set -x
cd "$(dirname "$0")/.."
LOG=/tmp/tpu_session.log
: > "$LOG"
echo "=== tunnel check $(date -u +%H:%M:%S) ===" >> "$LOG"
timeout 180 python -c "import jax; print(jax.devices())" >> "$LOG" 2>&1 || {
  echo "TUNNEL DOWN" >> "$LOG"; exit 1; }

echo "=== full bench ===" >> "$LOG"
rm -f /tmp/bench_refresh.json   # never let a stale run masquerade as this one
if BENCH_DEADLINE_S=4500 timeout 5400 python bench.py > /tmp/bench_refresh.json 2>> "$LOG"; then
  cp /tmp/bench_refresh.json BENCH_TUNNEL_RECOVERY.json
  git add BENCH_TUNNEL_RECOVERY.json
  git commit -m "Record tunnel-recovery bench artifact" -- BENCH_TUNNEL_RECOVERY.json >> "$LOG" 2>&1 || {
    echo "artifact commit failed; unstaging so it cannot ride another commit" >> "$LOG"
    git reset -q -- BENCH_TUNNEL_RECOVERY.json; }
else
  echo "bench.py failed or timed out; no BENCH_TUNNEL_RECOVERY.json" >> "$LOG"
fi

if [ -f tools/decode_profile_probe.py ]; then
  echo "=== decode profile probe ===" >> "$LOG"
  timeout 2400 python tools/decode_profile_probe.py >> "$LOG" 2>&1
fi
echo "=== int8 dequant probe ===" >> "$LOG"
timeout 1800 python tools/int8_dequant_probe.py >> "$LOG" 2>&1
echo "=== sampling cost probe ===" >> "$LOG"
timeout 1800 python tools/sampling_cost_probe.py >> "$LOG" 2>&1
echo "=== done $(date -u +%H:%M:%S) ===" >> "$LOG"

# land the probe log inside the repo so an end-of-round auto-commit
# preserves it even if no interactive session is alive to fold it in
{ echo "# Probe + bench results from the tunnel-recovery watcher."
  echo "# Produced by tools/tpu_session.sh at $(date -u +%FT%TZ)."
  cat "$LOG"; } > TUNNEL_RECOVERY_PROBES.log
git add TUNNEL_RECOVERY_PROBES.log
git commit -m "Record tunnel-recovery probe log" -- TUNNEL_RECOVERY_PROBES.log >> "$LOG" 2>&1 || {
  echo "log commit failed; unstaging" >> "$LOG"
  git reset -q -- TUNNEL_RECOVERY_PROBES.log; }
