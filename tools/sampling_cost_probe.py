"""Probe: how much of a decode step does SAMPLING eat at large batch?

The sweep in BENCH_SELF_r03 shows achieved GB/s falling as batch grows
(0.61 roofline at b8 -> 0.24 at b64).  Weights traffic is batch-invariant,
so the extra per-step time is activation work — and top-k over [b, 32000]
logits (lax.top_k sorts) is a prime suspect.  This times the SAME decode
loop under greedy / top-k=7 / top-p sampling to isolate that cost.

Run on the real device: ``python tools/sampling_cost_probe.py``.
"""

import time

import jax
import numpy as np

from distributed_inference_demo_tpu.models import get_model_config
from distributed_inference_demo_tpu.models.decoder import init_full_params
from distributed_inference_demo_tpu.ops.sampling import SamplingParams
from distributed_inference_demo_tpu.runtime import InferenceEngine
from distributed_inference_demo_tpu.telemetry.profiling import \
    dispatch_signature

try:        # `python tools/sampling_cost_probe.py` vs `-m tools....`
    from probe_artifact import emit_signatures
except ImportError:
    from tools.probe_artifact import emit_signatures


def main():
    cfg = get_model_config("tinyllama-1.1b")
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    variants = [
        ("greedy", SamplingParams(greedy=True)),
        ("topk7", SamplingParams(temperature=0.7, top_k=7)),
        ("topp95", SamplingParams(temperature=0.7, top_k=0, top_p=0.95)),
    ]
    rows = []
    for batch in (8, 64):
        for name, samp in variants:
            eng = InferenceEngine(cfg, params, max_seq=192, sampling=samp)
            prompt = (np.arange(batch * 64).reshape(batch, 64)
                      % 1000).astype(np.int32)
            eng.generate(prompt, 128, seed=0)            # compile
            r = eng.generate(prompt, 128, seed=0)
            steps = 128
            ms = r.seconds / steps * 1000
            print(f"b={batch:3d} {name:7s} {r.tokens_per_second:9.1f} tok/s"
                  f"  {ms:6.2f} ms/step", flush=True)
            rows.append((dispatch_signature(f"probe_sampling_{name}",
                                            batch=batch, chunk=steps),
                         {"mean_ms": ms,
                          "tokens_per_sec": r.tokens_per_second}))
    # observatory artifact: signature-keyed, mergeable (§20)
    emit_signatures(rows, extra={"probe": "sampling_cost"})


if __name__ == "__main__":
    main()
