#!/usr/bin/env python
"""Offline postmortem-bundle analyzer: bundle directory in, diagnosis out.

Reads a bundle written by ``telemetry/postmortem.py`` (manifest, flight
ring, metrics snapshot, run-log tail) and summarizes it down to the
offending hop/window:

- for a ``pipeline_stall``, each stalled (rid, step) is walked through
  its ``hop_send``/``hop_recv``/``tok_recv`` flight events; the LAST
  event pins the hop where the token step died — a trailing ``hop_send``
  from stage S to D means the message left S and D never processed it
  (D dead, or the S→D link down); a trailing ``hop_recv`` at S means S
  took the message and never forwarded (compute stalled mid-hop);
- for a ``crash``, the exception chain from the manifest plus the final
  ring events;
- always: the recorded anomalies, event counts over the capture window,
  and the ``dwt_anomaly_*`` counters from the metrics snapshot.

Run standalone (``python tools/postmortem.py <bundle_dir>`` for a human
summary, ``--json`` for machine output) or import ``summarize_bundle``
(the tier-1 smoke test runs it against a golden bundle in
``tests/data/golden_bundle``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional


def _load_json(path: str) -> Optional[dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def _load_jsonl(path: str) -> List[dict]:
    out: List[dict] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue    # a torn tail line is expected in a crash
    except OSError:
        pass
    return out


def _stalled_pairs(manifest: dict, events: List[dict]) -> List[List[int]]:
    """(rid, step) pairs still awaiting a reply, from the manifest detail
    or (fallback) the last ``pipeline_stall`` flight event."""
    detail = manifest.get("detail") or {}
    pairs = detail.get("in_flight")
    if not pairs:
        for e in reversed(events):
            if e.get("kind") == "pipeline_stall":
                pairs = e.get("in_flight")
                break
    return [[int(r), int(s)] for r, s in (pairs or [])]


def _diagnose_pair(rid: int, step: int,
                   events: List[dict]) -> Dict[str, object]:
    """Walk one (rid, step)'s hop events; the last one names the hop.

    The named hop is the FIRST UNCONFIRMED one from the capturing
    process's view: a bundle holds one process's flight ring, so when
    the trailing ``hop_send``'s destination never appears in this
    bundle's events at all (separate-process worker), the break is *at
    or after* that hop and the diagnosis says to continue the walk with
    the destination's own ring.  When the destination's ring IS in the
    bundle (in-process loopback, or a merged capture) its silence is
    conclusive."""
    chain = [e for e in events
             if e.get("rid") == rid and e.get("step") == step
             and e.get("kind") in ("hop_send", "hop_recv", "tok_recv")]
    chain.sort(key=lambda e: e.get("ts", 0))
    out: Dict[str, object] = {"rid": rid, "step": step,
                              "events": len(chain)}
    if not chain:
        out["offending_hop"] = "unknown (no hop events captured)"
        return out
    stages_seen = {e.get("stage") for e in events if e.get("stage")}
    last = chain[-1]
    out["last_event"] = last
    kind = last.get("kind")
    stage = last.get("stage", "?")
    if kind == "tok_recv":
        out["offending_hop"] = None     # reply made it back after all
    elif kind == "hop_send":
        dest = last.get("dest", "?")
        out["offending_hop"] = f"{stage}->{dest}"
        if dest in stages_seen:
            out["diagnosis"] = (f"stage {stage!r} sent (rid={rid}, "
                                f"step={step}) to {dest!r}, which never "
                                "processed it — dead stage or dead link")
        else:
            out["diagnosis"] = (
                f"stage {stage!r} sent (rid={rid}, step={step}) to "
                f"{dest!r} and no reply returned; this bundle holds only "
                f"{sorted(stages_seen)}'s ring, so the break is at or "
                f"after this hop — continue the walk with {dest!r}'s own "
                "flight ring (worker /debugz, or its crash bundle)")
    else:                               # hop_recv without a send
        out["offending_hop"] = f"{stage} (compute)"
        out["diagnosis"] = (f"stage {stage!r} received (rid={rid}, "
                            f"step={step}) and never forwarded — "
                            "compute stalled or the process died "
                            "mid-hop")
    return out


def _metrics_highlights(path: str) -> Dict[str, float]:
    """The ``dwt_anomaly_*`` samples from the bundle's metrics snapshot
    (the full file stays available for ad-hoc grepping)."""
    out: Dict[str, float] = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                if not line.startswith("dwt_anomaly_"):
                    continue
                name, _, value = line.rstrip("\n").rpartition(" ")
                try:
                    out[name] = float(value)
                except ValueError:
                    continue
    except OSError:
        pass
    return out


def summarize_bundle(bundle_dir: str) -> dict:
    """The analyzer core: bundle directory -> summary dict."""
    manifest = _load_json(os.path.join(bundle_dir, "manifest.json"))
    if manifest is None:
        raise FileNotFoundError(
            f"{bundle_dir!r} has no readable manifest.json — not a "
            "postmortem bundle")
    events = _load_jsonl(os.path.join(bundle_dir, "flight.jsonl"))
    runlog = _load_jsonl(os.path.join(bundle_dir, "runlog_tail.jsonl"))

    kinds: Dict[str, int] = {}
    for e in events:
        kinds[e.get("kind", "?")] = kinds.get(e.get("kind", "?"), 0) + 1
    ts = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]

    out: dict = {
        "bundle": bundle_dir,
        "reason": manifest.get("reason"),
        "ts": manifest.get("ts"),
        "iso": manifest.get("iso"),
        "detail": manifest.get("detail") or {},
        "flight_events": len(events),
        "event_kinds": kinds,
        "window_s": round(max(ts) - min(ts), 6) if ts else 0.0,
        "anomalies": [e for e in events if e.get("kind") == "anomaly"],
        "metrics": _metrics_highlights(
            os.path.join(bundle_dir, "metrics.prom")),
    }

    # chaos runs: injected-fault flight events + the fault named in the
    # manifest (injected_fault_crash bundles) — the bundle states its own
    # cause so a failing soak is replayable from seed + plan alone
    injected = [e for e in events if e.get("kind") in
                ("fault_injected", "corrupt_frame")]
    detail = manifest.get("detail") or {}
    if injected or "fault" in detail:
        out["injected_faults"] = injected
        cause = detail.get("fault") or (injected[-1] if injected else None)
        if cause is not None:
            out["injected_cause"] = cause
        if "plan_seed" in detail:
            out["fault_plan_seed"] = detail["plan_seed"]

    stalled = [_diagnose_pair(r, s, events)
               for r, s in _stalled_pairs(manifest, events)]
    stalled = [d for d in stalled if d.get("offending_hop") is not None]
    if stalled:
        out["stalled"] = stalled
        # the headline answer: the hop most stalled steps died on
        hops = [d["offending_hop"] for d in stalled]
        out["offending_hop"] = max(set(hops), key=hops.count)

    if manifest.get("reason") == "crash":
        d = manifest.get("detail") or {}
        out["crash"] = {"exc_type": d.get("exc_type"),
                        "exc": d.get("exc"),
                        "thread": d.get("thread")}

    if runlog:
        out["runlog"] = {"lines": len(runlog), "last": runlog[-1]}
    return out


def format_summary(s: dict) -> str:
    lines = [
        f"postmortem bundle: {s['bundle']}",
        f"  reason: {s['reason']}  at {s.get('iso') or s.get('ts')}",
        f"  flight events: {s['flight_events']} over "
        f"{s['window_s']}s  kinds: "
        + ", ".join(f"{k}={v}" for k, v in sorted(s["event_kinds"]
                                                  .items())),
    ]
    if s.get("offending_hop"):
        lines.append(f"  OFFENDING HOP: {s['offending_hop']}")
        for d in s.get("stalled", []):
            lines.append(
                f"    rid={d['rid']} step={d['step']}: "
                f"{d.get('diagnosis', d['offending_hop'])}")
    if s.get("crash"):
        c = s["crash"]
        lines.append(f"  CRASH: {c.get('exc_type')}: {c.get('exc')}"
                     + (f" (thread {c['thread']})" if c.get("thread")
                        else ""))
    if s.get("injected_cause") is not None:
        c = s["injected_cause"]
        lines.append(
            # plan events carry the rule kind as "kind"; flight events as
            # "fault_kind" (their kind is the event type itself)
            f"  INJECTED FAULT: {c.get('fault_kind') or c.get('kind')} "
            f"device={c.get('device', c.get('stage', '?'))} "
            f"peer={c.get('peer')} tag={c.get('tag')}"
            + (f" (fault plan seed {s['fault_plan_seed']} — replay with "
               "the same seed)" if "fault_plan_seed" in s else ""))
    elif s.get("injected_faults"):
        lines.append(f"  injected faults in window: "
                     f"{len(s['injected_faults'])} (chaos run)")
    for a in s.get("anomalies", []):
        lines.append(f"  anomaly: {a.get('anomaly')} "
                     f"severity={a.get('severity')}")
    if s.get("metrics"):
        lines.append("  metrics: "
                     + ", ".join(f"{k}={v:g}" for k, v
                                 in sorted(s["metrics"].items())))
    if s.get("runlog"):
        lines.append(f"  runlog tail: {s['runlog']['lines']} lines, "
                     f"last event {s['runlog']['last'].get('event')!r}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="summarize a postmortem bundle down to the "
                    "offending hop/window")
    ap.add_argument("bundle", help="bundle directory "
                                   "(pm-<stamp>-<seq>-<reason>/)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable summary on stdout")
    args = ap.parse_args(argv)
    try:
        s = summarize_bundle(args.bundle)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 1
    print(json.dumps(s, default=str) if args.json else format_summary(s))
    return 0


if __name__ == "__main__":
    sys.exit(main())
