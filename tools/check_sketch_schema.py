"""Lint: the sketch recorder and the planner pin ONE schema version.

``telemetry/profiling.py`` (the recorder) and ``planner/planner.py``
(the consumer) each carry a LITERAL copy of ``SKETCH_SCHEMA_VERSION``
and ``SKETCH_REQUIRED_KEYS`` — deliberately duplicated so the planner
can parse committed artifacts without importing the serving stack.
This lint (tier-1, via tests/test_profiling.py) reads both copies by
AST — no imports, so it works on a box with neither jax nor the repo
installed — and fails when they disagree.

Run: ``python tools/check_sketch_schema.py`` (exit 0 = agree).
"""

import ast
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parents[1]
PKG = REPO / "distributed_inference_demo_tpu"
FILES = (PKG / "telemetry" / "profiling.py",
         PKG / "planner" / "planner.py")
NAMES = ("SKETCH_SCHEMA_VERSION", "SKETCH_REQUIRED_KEYS")


def pinned_constants(path: pathlib.Path) -> dict:
    """Module-level literal assignments for NAMES, by AST."""
    tree = ast.parse(path.read_text(), filename=str(path))
    out = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Name) and tgt.id in NAMES:
                out[tgt.id] = ast.literal_eval(node.value)
    return out


def check() -> list:
    """Return a list of error strings (empty = the copies agree)."""
    errors = []
    pins = {}
    for path in FILES:
        got = pinned_constants(path)
        missing = [n for n in NAMES if n not in got]
        if missing:
            errors.append(f"{path.relative_to(REPO)}: missing pinned "
                          f"constants {missing}")
            continue
        pins[path] = got
    if len(pins) == len(FILES):
        a, b = (pins[f] for f in FILES)
        for name in NAMES:
            va, vb = a[name], b[name]
            if isinstance(va, (list, tuple)):
                va, vb = tuple(va), tuple(vb)
            if va != vb:
                errors.append(
                    f"{name} disagrees: "
                    f"{FILES[0].relative_to(REPO)} pins {a[name]!r}, "
                    f"{FILES[1].relative_to(REPO)} pins {b[name]!r} — "
                    "bump BOTH copies together")
    return errors


def main() -> int:
    errors = check()
    for e in errors:
        print(f"check_sketch_schema: {e}", file=sys.stderr)
    if not errors:
        print("check_sketch_schema: recorder and planner agree "
              f"(schema v{pinned_constants(FILES[0])['SKETCH_SCHEMA_VERSION']})")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
