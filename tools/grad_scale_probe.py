"""Property probe: pipeline-parallel gradients vs single-device gradients.

Verifies, leaf by leaf, that the SPMD circular pipeline's raw gradients are
the single-device gradients scaled uniformly by ``pp * tp`` — the rule
``make_pipeline_train_step`` normalizes by (see the derivation in
``parallel/pipeline.py``).  Runs in its own process so it can force an
arbitrary virtual device count (the test suite's conftest pins 8).

    python tools/grad_scale_probe.py --pp 4 --tp 4

Prints one JSON line: {"pp", "tp", "expected", "ratios": [...], "uniform"}.
Exit code 0 iff every leaf's median ratio equals pp*tp within 1% and the
per-leaf spread is under 2%.
"""

import argparse
import json
import os
import sys


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--pp", type=int, default=2)
    ap.add_argument("--tp", type=int, default=2)
    ap.add_argument("--microbatches", type=int, default=2)
    args = ap.parse_args()
    n = args.pp * args.tp

    os.environ["XLA_FLAGS"] = f"--xla_force_host_platform_device_count={n}"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    jax.config.update("jax_platforms", "cpu")

    import distributed_inference_demo_tpu.parallel.pipeline as pl
    from distributed_inference_demo_tpu.models import (
        KVCache, StageSpec, get_model_config)
    from distributed_inference_demo_tpu.models.decoder import (
        init_full_params, stage_forward)
    from distributed_inference_demo_tpu.parallel import MeshConfig, make_mesh

    # nkv=4 so tp up to 4 shards the kv heads evenly
    cfg = get_model_config("llama-test").replace(num_heads=8,
                                                 num_kv_heads=4)
    params = init_full_params(jax.random.PRNGKey(0), cfg)
    B, S = 8, 8
    ids = jax.random.randint(jax.random.PRNGKey(2), (B, S), 0,
                             cfg.vocab_size, jnp.int32)
    targets = jnp.roll(ids, -1, axis=1).at[:, -1].set(-100)

    def ref_loss(p):
        spec = StageSpec(0, 1, 0, cfg.num_layers)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        logits, _ = stage_forward(
            p, cfg, spec, ids, KVCache.create(cfg, cfg.num_layers, B, S),
            pos)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        mask = targets != -100
        ll = jnp.take_along_axis(logp, jnp.maximum(targets, 0)[..., None],
                                 -1)[..., 0]
        return -jnp.sum(jnp.where(mask, ll, 0)) / jnp.sum(mask)

    ref_grads = jax.grad(ref_loss)(params)

    mesh = make_mesh(MeshConfig(pp=args.pp, tp=args.tp), jax.devices()[:n])
    use_tp = args.tp > 1
    in_specs_params = pl._pp_in_specs(params, cfg, use_tp)
    sync_axes = pl._grad_sync_axes(params, cfg, use_tp)

    def sm(params_local, ids_mb, targets_mb):
        loss, grads = jax.value_and_grad(
            lambda p: pl.pipeline_apply(cfg, p, ids_mb, targets_mb,
                                        "tp" if use_tp else None)
        )(params_local)
        grads = jax.tree.map(
            lambda g, axes: jax.lax.psum(g, axes) if axes else g,
            grads, sync_axes)
        return loss, grads

    sharded = jax.shard_map(sm, mesh=mesh,
                            in_specs=(in_specs_params, P(), P()),
                            out_specs=(P(), in_specs_params),
                            check_vma=False)
    M = args.microbatches
    with mesh:
        _, grads = sharded(params, ids.reshape(M, B // M, S),
                           targets.reshape(M, B // M, S))

    def flat(tree):
        return {jax.tree_util.keystr(k): v for k, v in
                jax.tree_util.tree_leaves_with_path(tree)}

    refd, gd = flat(ref_grads), flat(grads)
    expected = float(args.pp * args.tp)
    report = []
    uniform = True
    for k, g in gd.items():
        r = np.asarray(g, np.float64).ravel()
        rr = np.asarray(refd[k], np.float64).ravel()
        m = np.abs(rr) > 1e-5
        if not m.any():
            continue
        q = r[m] / rr[m]
        med = float(np.median(q))
        spread = float(np.percentile(np.abs(q - med), 95))
        ok = abs(med - expected) <= 0.01 * expected and \
            spread <= 0.02 * max(1.0, abs(med))
        uniform &= ok
        report.append({"leaf": k, "median": round(med, 4),
                       "spread95": round(spread, 5), "ok": ok})
    print(json.dumps({"pp": args.pp, "tp": args.tp, "expected": expected,
                      "uniform": uniform,
                      "ratios": sorted({r["median"] for r in report}),
                      "leaves": len(report),
                      "bad": [r for r in report if not r["ok"]]}))
    return 0 if uniform else 1


if __name__ == "__main__":
    sys.exit(main())
