#!/usr/bin/env python
"""Rejection-matrix lint: the paged KV layout is UNIVERSAL.

PR 4 shipped the paged page pool behind an explicit rejection matrix —
ten ``require_dense_kv_layout`` call sites across the engines and CLI
(DESIGN.md §11).  PR 7 dissolved it: every engine and CLI mode accepts
``--kv-layout paged`` (the default), and ``require_dense_kv_layout``
survives only inside ``runtime/kvcache/`` as a legacy shim for
out-of-tree callers.

This lint keeps the matrix from silently regrowing: no production
module outside ``runtime/kvcache/`` may reference
``require_dense_kv_layout`` (a new dense-only mode must either grow
paged plumbing or raise its own documented error with its own test).
Walks every ``.py`` under the package, source-level — a call site that
never executes on the lint's import path still counts.

Run standalone (``python tools/check_kv_layout.py``, exit 1 on
violations) or via the tier-1 suite (``tests/test_metrics_names.py``).
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

PACKAGE = "distributed_inference_demo_tpu"
ALLOWED_SUBTREE = ("runtime", "kvcache")   # the shim's home


def check_kv_layout_matrix(root: pathlib.Path) -> List[str]:
    """Return human-readable violations (empty = matrix still empty)."""
    problems: List[str] = []
    pkg = root / PACKAGE
    for path in sorted(pkg.rglob("*.py")):
        rel = path.relative_to(root)
        if rel.parts[1:3] == ALLOWED_SUBTREE:
            continue
        text = path.read_text(encoding="utf-8")
        for lineno, line in enumerate(text.splitlines(), 1):
            if "require_dense_kv_layout" in line:
                problems.append(
                    f"{rel}:{lineno}: references "
                    "require_dense_kv_layout — the §11 rejection matrix "
                    "is dissolved (DESIGN.md §14); paged must be "
                    "accepted, not rejected")
    return problems


def main() -> int:
    root = pathlib.Path(__file__).resolve().parents[1]
    problems = check_kv_layout_matrix(root)
    for p in problems:
        print(f"KV LAYOUT LINT: {p}", file=sys.stderr)
    if problems:
        print(f"{len(problems)} rejection-matrix violation(s)",
              file=sys.stderr)
        return 1
    print("kv layout matrix OK (no require_dense_kv_layout call sites "
          f"outside {PACKAGE}/runtime/kvcache/)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
