#!/usr/bin/env python
"""Dense-removal lint: the paged KV layout is the ONLY layout.

PR 4 shipped the paged page pool behind an explicit rejection matrix —
ten ``require_dense_kv_layout`` call sites across the engines and CLI
(DESIGN.md §11).  PR 7 dissolved it, PR 8 deprecated the dense escape
hatch for one release, and the gateway PR deleted it: the dense
backend class, the legacy require-dense shim, and ``--kv-layout
dense`` resolution are gone (resolving "dense" fails loudly naming
the removal).

This lint keeps the deletion deleted: NO module in the package — the
kvcache subtree included, since the shim's home is gone too — may
reference either removed identifier.  A new dense-only mode must grow
its own documented error with its own test, not resurrect the old
names.  Walks every ``.py`` under the package, source-level — a call
site that never executes on the lint's import path still counts.

Run standalone (``python tools/check_kv_layout.py``, exit 1 on
violations) or via the tier-1 suite (``tests/test_metrics_names.py``).
"""

from __future__ import annotations

import pathlib
import sys
from typing import List

PACKAGE = "distributed_inference_demo_tpu"

# identifiers deleted with the dense escape hatch; zero references may
# remain anywhere in the package (ISSUE 10 acceptance)
REMOVED_IDENTIFIERS = ("require_dense_kv_layout", "DenseKVBackend")


def check_kv_layout_matrix(root: pathlib.Path) -> List[str]:
    """Return human-readable violations (empty = removal holds)."""
    problems: List[str] = []
    pkg = root / PACKAGE
    for path in sorted(pkg.rglob("*.py")):
        rel = path.relative_to(root)
        text = path.read_text(encoding="utf-8")
        for lineno, line in enumerate(text.splitlines(), 1):
            for ident in REMOVED_IDENTIFIERS:
                if ident in line:
                    problems.append(
                        f"{rel}:{lineno}: references {ident} — deleted "
                        "with the dense escape hatch (DESIGN.md §14); "
                        "paged is the only layout")
    return problems


def main() -> int:
    root = pathlib.Path(__file__).resolve().parents[1]
    problems = check_kv_layout_matrix(root)
    for p in problems:
        print(f"KV LAYOUT LINT: {p}", file=sys.stderr)
    if problems:
        print(f"{len(problems)} dense-removal violation(s)",
              file=sys.stderr)
        return 1
    print("kv layout OK (no references to removed dense identifiers "
          f"anywhere under {PACKAGE}/)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
