"""Shared emitter for the ad-hoc probe scripts (docs/DESIGN.md §20).

Every probe keeps its human-readable prints, then emits ONE trailing
JSON artifact keyed by the observatory's dispatch-signature schema
(``telemetry/profiling.dispatch_signature``) so probe outputs merge
with ``/debugz`` observatory snapshots and bench ``dispatch_profile``
extras blocks: the join key is the signature string, the values are
per-signature summaries (``*_ms``, ``*_gbs``, counts).

Canonical rendering (sorted keys, minimal separators) matches the
sketch artifact contract — piping a probe's last line into a file
yields a committable, diffable artifact.
"""

import json


def signature_entries(rows):
    """``[(signature, {metric: value})] -> {signature: {...}}`` with
    floats rounded (determinism) and later duplicates merged into
    earlier ones (a probe timing one signature twice updates it)."""
    out = {}
    for sig, metrics in rows:
        e = out.setdefault(sig, {})
        for k, v in metrics.items():
            e[k] = round(v, 6) if isinstance(v, float) else v
    return out


def emit_signatures(rows, extra=None):
    """Print the trailing observatory artifact for ``rows`` =
    ``[(signature, metrics_dict)]``; ``extra`` merges into the top
    level (probe-specific context like weights_gb)."""
    obj = {"schema": "dispatch_signature",
           "signatures": signature_entries(rows)}
    if extra:
        obj.update(extra)
    print("== observatory artifact ==", flush=True)
    print(json.dumps(obj, sort_keys=True, separators=(",", ":")),
          flush=True)
