"""Probe: where does int8 decode lose bandwidth vs bf16?

Runs a decode-shaped workload (scan over stacked layers, matvec per layer,
repeated token steps inside one dispatch) on the real device and compares:

- bf16 weights (reference traffic)
- int8 via f32 intermediate dequant (current ops/quant.py dense())
- int8 via direct-to-bf16 dequant (q.astype(bf16) * scale.astype(bf16))

Prints GB/s achieved per variant counting each variant's true weight bytes.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_inference_demo_tpu.telemetry.profiling import \
    dispatch_signature

try:        # `python tools/int8_dequant_probe.py` vs `-m tools....`
    from probe_artifact import emit_signatures
except ImportError:
    from tools.probe_artifact import emit_signatures

L, H, I = 8, 2048, 5632
B = 8
STEPS = 24


def bench(fn, *args):
    out = fn(*args)
    np.asarray(out)                       # compile + hard sync
    t0 = time.perf_counter()
    out = fn(*args)
    np.asarray(out)
    return time.perf_counter() - t0


def main():
    rng = np.random.default_rng(0)
    w_up = jnp.asarray(rng.standard_normal((L, H, I), dtype=np.float32),
                       jnp.bfloat16)
    w_dn = jnp.asarray(rng.standard_normal((L, I, H), dtype=np.float32),
                       jnp.bfloat16)
    x0 = jnp.asarray(rng.standard_normal((B, H), dtype=np.float32),
                     jnp.bfloat16)

    def tok_scan(layer_fn, weights):
        @jax.jit
        def run(x):
            def tok(x, _):
                def lay(x, ws):
                    return layer_fn(x, ws), None
                x, _ = jax.lax.scan(lay, x, weights)
                return x, None
            x, _ = jax.lax.scan(tok, x, None, length=STEPS)
            return x
        return run

    # bf16 reference
    def lay_bf16(x, ws):
        wu, wd = ws
        h = jnp.maximum(x @ wu, 0)
        return (h @ wd).astype(jnp.bfloat16)

    rows = []

    def note(variant, kv_dtype, dt, nbytes):
        rows.append((dispatch_signature(f"probe_dequant_{variant}",
                                        batch=B, chunk=STEPS,
                                        kv_dtype=kv_dtype),
                     {"mean_ms": dt * 1e3 / STEPS,
                      "achieved_gbs": nbytes * STEPS / dt / 1e9}))

    dt = bench(tok_scan(lay_bf16, (w_up, w_dn)), x0)
    nbytes = (w_up.nbytes + w_dn.nbytes)
    print(f"bf16:        {dt*1e3/STEPS:7.2f} ms/step  "
          f"{nbytes*STEPS/dt/1e9:7.1f} GB/s")
    note("bf16", "bf16", dt, nbytes)

    # int8 quantize
    def q(w):
        a = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=1, keepdims=True)
        s = jnp.maximum(a, 1e-8) / 127.0
        qq = jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127,
                      127).astype(jnp.int8)
        return qq, s.astype(jnp.float32)

    qu, su = q(w_up)
    qd, sd = q(w_dn)
    q_bytes = qu.nbytes + qd.nbytes + su.nbytes + sd.nbytes

    def lay_f32(x, ws):
        qu, su, qd, sd = ws
        wu = (qu.astype(jnp.float32) * su).astype(jnp.bfloat16)
        wd = (qd.astype(jnp.float32) * sd).astype(jnp.bfloat16)
        h = jnp.maximum(x @ wu, 0)
        return (h @ wd).astype(jnp.bfloat16)

    dt = bench(tok_scan(lay_f32, (qu, su, qd, sd)), x0)
    print(f"int8 f32-deq:{dt*1e3/STEPS:7.2f} ms/step  "
          f"{q_bytes*STEPS/dt/1e9:7.1f} GB/s")
    note("f32_deq", "int8", dt, q_bytes)

    def lay_bf(x, ws):
        qu, su, qd, sd = ws
        wu = qu.astype(jnp.bfloat16) * su.astype(jnp.bfloat16)
        wd = qd.astype(jnp.bfloat16) * sd.astype(jnp.bfloat16)
        h = jnp.maximum(x @ wu, 0)
        return (h @ wd).astype(jnp.bfloat16)

    dt = bench(tok_scan(lay_bf, (qu, su, qd, sd)), x0)
    print(f"int8 bf-deq: {dt*1e3/STEPS:7.2f} ms/step  "
          f"{q_bytes*STEPS/dt/1e9:7.1f} GB/s")
    note("bf_deq", "int8", dt, q_bytes)

    # int8 with dot_general on raw int8 then scale the [B, I] result
    # (per-output-channel scale commutes past the contraction)
    def lay_post(x, ws):
        qu, su, qd, sd = ws
        h = jax.lax.dot_general(
            x.astype(jnp.bfloat16), qu.astype(jnp.bfloat16),
            (((1,), (0,)), ((), ())))
        h = jnp.maximum(h * su[0].astype(jnp.bfloat16), 0)
        o = jax.lax.dot_general(
            h, qd.astype(jnp.bfloat16), (((1,), (0,)), ((), ())))
        return (o * sd[0].astype(jnp.bfloat16)).astype(jnp.bfloat16)

    dt = bench(tok_scan(lay_post, (qu, su, qd, sd)), x0)
    print(f"int8 post-sc:{dt*1e3/STEPS:7.2f} ms/step  "
          f"{q_bytes*STEPS/dt/1e9:7.1f} GB/s")
    note("post_scale", "int8", dt, q_bytes)

    # observatory artifact: signature-keyed, mergeable (§20)
    emit_signatures(rows, extra={"probe": "int8_dequant"})


if __name__ == "__main__":
    main()
