#!/usr/bin/env python
"""Metric-name lint: every registered Prometheus series must follow the
repo convention (docs/DESIGN.md §7).

Rules, checked against the default registry after importing
``telemetry.catalog`` (which registers the full standard set at import
time):

1. names are ``dwt_<subsystem>_<rest>`` — the ``dwt_`` prefix namespaces
   the repo and ``<subsystem>`` must be a known subsystem;
2. the name ends in a recognized unit suffix (counters may follow the
   unit with Prometheus's ``_total``); dimensionless gauges must say so
   (``_ratio`` / bare count units like ``_slots``);
3. every metric has non-empty help text (enforced structurally by
   ``metrics.Metric`` — re-checked here so a future constructor bypass
   still fails the lint);
4. counters end in ``_total``; non-counters must NOT (the Prometheus
   convention scrapers and recording rules rely on);
5. label sets are linted too: label names come from a known vocabulary
   (a typo'd label forks a series family no dashboard joins), the
   fleet-plane families' label sets are pinned exactly
   (``tenant=``/``replica=`` must stay catalog-declared), and
   ``replica`` is reserved for the ``/metrics/fleet`` relabeler on
   non-gateway series.

Run standalone (``python tools/check_metrics_names.py``, exit 1 on
violations) or via the tier-1 suite (``tests/test_metrics_names.py``).
"""

from __future__ import annotations

import sys
from typing import List

SUBSYSTEMS = {"stage", "batching", "speculative", "http", "monitor",
              "engine", "control", "anomaly", "flight", "kvcache",
              "transport", "fault", "disagg", "gateway", "migration",
              "slo", "profile", "compile", "hbm"}

# unit suffixes a metric name may end with (after stripping ``_total``).
# Plain-count units (requests, tokens, ...) double as the unit for
# occupancy gauges (queue depth in requests, capacity in slots).
UNITS = {"seconds", "bytes", "messages", "steps", "tokens", "requests",
         "rounds", "hits", "misses", "slots", "spans", "entries",
         "ratio", "bytes_per_second", "flops_per_second", "celsius",
         "info", "events", "bundles", "blocks", "nodes",
         "retries", "reconnects", "frames", "faults", "dispatches",
         "pages", "replicas", "scrapes", "samples", "attempts",
         "failures"}

# label names any series may declare.  The label VOCABULARY is linted
# like the name vocabulary: a typo'd label ("tenent", "repilca") would
# silently fork a series family that no dashboard joins, which is worse
# than a crash.  Extend deliberately, with the catalog.
KNOWN_LABELS = {"role", "device", "route", "code", "kind", "engine",
                "peer", "replica", "dtype", "tenant", "window",
                "signature", "program", "owner", "tier", "bucket",
                "reason"}

# series whose label SET is pinned exactly — the fleet-plane families
# whose labels dashboards and the federation relabeler join on.  A
# tenant series silently losing its tenant label (or growing a stray
# one) would still render, still scrape, and aggregate every tenant
# into one line — this lint makes that drift a tier-1 failure.
REQUIRED_LABELS = {
    "dwt_slo_ttft_seconds": ("tenant",),
    "dwt_slo_queue_wait_seconds": ("tenant",),
    "dwt_slo_per_token_seconds": ("tenant",),
    "dwt_slo_e2e_seconds": ("tenant",),
    "dwt_slo_migration_pause_seconds": ("tenant",),
    "dwt_slo_requests_total": ("tenant",),
    "dwt_slo_failed_requests_total": ("tenant",),
    "dwt_slo_tokens_total": ("tenant",),
    "dwt_slo_good_tokens_total": ("tenant",),
    "dwt_slo_good_ttft_requests_total": ("tenant",),
    "dwt_slo_migrated_requests_total": ("tenant",),
    "dwt_slo_burn_rate_ratio": ("tenant", "window"),
    "dwt_gateway_fleet_scrapes_total": ("replica",),
    "dwt_gateway_fleet_failed_scrapes_total": ("replica",),
    "dwt_gateway_fleet_scrape_age_seconds": ("replica",),
    "dwt_gateway_prefix_hit_ratio": ("replica",),
    "dwt_gateway_index_entries": ("replica",),
    "dwt_gateway_queue_depth_requests": ("replica",),
    "dwt_anomaly_events_total": ("kind",),
    "dwt_anomaly_last_seconds": ("kind",),
    # cost observatory (docs/DESIGN.md §20): the dispatch-signature /
    # program / owner keys ARE the join keys the auto-planner and
    # fleet_top --profile aggregate on — losing one collapses every
    # program variant (or pool owner) into a single meaningless line
    "dwt_profile_dispatch_seconds": ("signature",),
    "dwt_profile_samples_total": ("signature",),
    "dwt_profile_dispatches_total": ("signature",),
    "dwt_profile_achieved_bytes_per_second": ("signature",),
    "dwt_profile_roofline_ratio": ("signature",),
    "dwt_compile_events_total": ("program",),
    "dwt_compile_seconds_total": ("program",),
    "dwt_compile_cache_entries": ("program",),
    "dwt_compile_variant_budget_entries": ("program",),
    "dwt_hbm_owner_bytes": ("owner",),
    "dwt_hbm_watermark_bytes": ("owner",),
    # tiered KV (docs/DESIGN.md §21): the tier label (host / disk) is
    # what separates "RAM is full" from "disk is full" on a dashboard —
    # an unlabeled residency gauge would sum the two budgets into one
    # meaningless number
    "dwt_kvcache_tier_resident_bytes": ("tier",),
    "dwt_kvcache_tier_resident_blocks": ("tier",),
    "dwt_kvcache_tier_capacity_bytes": ("tier",),
    "dwt_kvcache_tier_hits_total": ("tier",),
    # zero-loss streams (docs/DESIGN.md §23): resume pause is a tenant
    # SLO dimension like migration pause, and the failure-reason label
    # is the bounded vocabulary /debugz and dashboards break down on —
    # losing it would fold probe flakes and mid-stream deaths into one
    # undiagnosable count
    "dwt_slo_resume_pause_seconds": ("tenant",),
    "dwt_slo_resumed_requests_total": ("tenant",),
    "dwt_gateway_replica_failures_total": ("reason",),
}

# label names reserved for the federation relabeler: GET /metrics/fleet
# injects replica="<rid>" into every replica-exported sample, so a
# REPLICA-side series already carrying the label would collide with the
# injected one (Prometheus rejects duplicate label names in a sample).
# Gateway-side series (dwt_gateway_*) legitimately declare it — they
# are emitted by the gateway's own registry, never relabeled.
FEDERATION_RESERVED_LABELS = {"replica"}

# exact names exempted from the unit-suffix rule — each entry is a
# deliberate, documented exception (NOT a new unit: adding a pseudo-unit
# would let every future misnamed series ending the same way slip
# through).  dwt_kvcache_blocks_in_use carries its unit (blocks) mid-
# name; it pairs with dwt_kvcache_used_blocks as the all-owners gauge
# (docs/DESIGN.md §11 runbook).  The gateway replica-transition pair
# carries its unit (replicas) mid-name too: the ISSUE-10 acceptance
# pins the exact name dwt_gateway_replica_down_total, and up/down name
# the transition direction where the unit would sit.
UNIT_SUFFIX_EXEMPT = {"dwt_kvcache_blocks_in_use",
                      "dwt_gateway_replica_down_total",
                      "dwt_gateway_replica_up_total",
                      # ISSUE-15 pins this exact name: a dimensionless
                      # packed/budgeted fraction (a _ratio in spirit;
                      # "utilization" is the roofline-adjacent term the
                      # §19 runbook and bench leg both use)
                      "dwt_batching_token_budget_utilization",
                      # ISSUE-19 pins this exact name: the per-bucket
                      # adaptive-K occupancy gauge — "len" is the
                      # quantity itself (a draft LENGTH bucket), the
                      # value's unit is rows via the bucket label
                      "dwt_batching_draft_len",
                      # ISSUE-20 pins this exact name: the resumes that
                      # finished the stream — "succeeded" names the
                      # outcome where the unit would sit, pairing with
                      # dwt_gateway_resume_attempts_total
                      "dwt_gateway_resume_succeeded_total"}

# series the catalog must always register (regressions here would blind
# the flight-recorder/anomaly layer silently — a scrape with the series
# simply absent looks exactly like a healthy quiet system).  The
# dwt_kvcache_* block is required the same way: a serving stack whose
# cache section vanished from /metrics reads as "cache disabled", which
# is indistinguishable from "prefix reuse silently regressed".
REQUIRED_SERIES = {
    "dwt_flight_events_total",
    "dwt_flight_buffer_events",
    "dwt_anomaly_events_total",
    "dwt_anomaly_last_seconds",
    "dwt_anomaly_postmortem_bundles_total",
    "dwt_kvcache_hits_total",
    "dwt_kvcache_misses_total",
    "dwt_kvcache_partial_hit_tokens_total",
    "dwt_kvcache_stored_blocks_total",
    "dwt_kvcache_evicted_blocks_total",
    "dwt_kvcache_resident_bytes",
    "dwt_kvcache_tree_nodes",
    # the paged-layout triple (docs/DESIGN.md §11): device residency and
    # the h2d counter whose staying-at-zero IS the paged path's claim —
    # their absence would make "zero-copy prefix hits" unverifiable
    "dwt_kvcache_device_resident_bytes",
    "dwt_kvcache_blocks_in_use",
    "dwt_kvcache_h2d_bytes_total",
    "dwt_kvcache_page_dtype_info",
    "dwt_kvcache_quant_scale_bytes",
    # the §21 tier triple: residency plus the demote/promote flow
    # counters — a tier silently absent from /metrics reads as
    # "tiering disabled", indistinguishable from "demotions regressed"
    "dwt_kvcache_tier_resident_bytes",
    "dwt_kvcache_tier_promoted_blocks_total",
    "dwt_kvcache_tier_demoted_blocks_total",
    # the transport-reliability / chaos quartet (docs/DESIGN.md §12): a
    # corrupt frame that is silently absent from /metrics is exactly the
    # "decoded garbage into a wrong token" failure this layer exists to
    # rule out, and dwt_fault_* staying registered-and-zero is how a
    # production scrape PROVES no fault plan leaked into the process
    "dwt_transport_send_retries_total",
    "dwt_transport_reconnects_total",
    "dwt_transport_corrupt_frames_total",
    "dwt_fault_injected_faults_total",
    # the mixed-dispatch triple (docs/DESIGN.md §19): utilization absent
    # would make "the budget is actually being packed" unverifiable, and
    # mixed_dispatches staying registered-and-zero is how a scrape PROVES
    # an engine is running the serialized interleave, not mixed mode
    "dwt_batching_mixed_dispatches_total",
    "dwt_batching_mixed_prefill_tokens_total",
    "dwt_batching_token_budget_utilization",
    # the spec-in-the-batch quartet (docs/DESIGN.md §22): drafted /
    # accepted absent would make the acceptance collapse the adaptive-K
    # loop reacts to unobservable, and the draft_len bucket gauge
    # registered-and-zero is how a scrape PROVES no row is speculating
    "dwt_batching_draft_tokens_total",
    "dwt_batching_accepted_tokens_total",
    "dwt_batching_draft_len",
    "dwt_batching_spec_acceptance_ratio",
    # the device-loop pair (docs/DESIGN.md §13): dispatches/token ≈ 1/K
    # is the dispatch-floor claim — with either series absent, a fused
    # loop that silently fell back to per-token dispatch would scrape
    # exactly like a healthy one
    "dwt_engine_host_dispatches_total",
    "dwt_engine_device_loop_steps_total",
    # the disaggregation set (docs/DESIGN.md §15): migrated vs adopted
    # pages diverging is the wedged-handoff signal, and rescheduled
    # staying registered-and-zero is how a scrape PROVES no prefill
    # worker silently died mid-migration
    "dwt_disagg_migrated_pages_total",
    "dwt_disagg_migrated_bytes_total",
    "dwt_disagg_adopted_pages_total",
    "dwt_disagg_rescheduled_requests_total",
    "dwt_disagg_migration_seconds",
    "dwt_disagg_handoff_queue_depth_requests",
    # the gateway set (docs/DESIGN.md §16): replica_down staying
    # registered-and-zero is how a scrape PROVES no replica was
    # evicted, and routed/hashed/retried absent would make the
    # cache-aware-vs-fallback split (the subsystem's whole point)
    # unobservable
    "dwt_gateway_prefix_routed_requests_total",
    "dwt_gateway_hashed_requests_total",
    "dwt_gateway_retried_requests_total",
    "dwt_gateway_shed_requests_total",
    "dwt_gateway_replica_down_total",
    "dwt_gateway_replica_up_total",
    "dwt_gateway_up_replicas",
    "dwt_gateway_proxy_ttft_seconds",
    # draining (docs/DESIGN.md §18): a drain whose gauge vanished from
    # /metrics reads as "nothing draining" — exactly the stuck-drain
    # incident the gauge exists to surface
    "dwt_gateway_draining_replicas",
    # the live-migration set (docs/DESIGN.md §18): exported vs imported
    # diverging is the failed-admission signal, replayed staying
    # registered-and-zero is how a scrape PROVES the atomic handoff
    # never re-emitted a step to a client, and inflight stuck nonzero
    # names a wedged migration path
    "dwt_migration_exported_requests_total",
    "dwt_migration_imported_requests_total",
    "dwt_migration_aborted_requests_total",
    "dwt_migration_replayed_steps_total",
    "dwt_migration_moved_pages_total",
    "dwt_migration_moved_bytes_total",
    "dwt_migration_handoff_seconds",
    "dwt_migration_inflight_requests",
    # the fleet observability plane (docs/DESIGN.md §7): per-tenant SLO
    # accounting absent from a scrape is indistinguishable from "no
    # tenant ever violated its SLO", and the federation counters absent
    # would make a dead replica's section silently vanish from
    # /metrics/fleet with nothing left to alert on
    "dwt_slo_requests_total",
    "dwt_slo_tokens_total",
    "dwt_slo_good_tokens_total",
    "dwt_slo_ttft_seconds",
    "dwt_slo_per_token_seconds",
    "dwt_slo_e2e_seconds",
    "dwt_slo_migration_pause_seconds",
    "dwt_slo_burn_rate_ratio",
    "dwt_gateway_fleet_scrapes_total",
    "dwt_gateway_fleet_failed_scrapes_total",
    "dwt_gateway_fleet_scrape_age_seconds",
    # the cost observatory (docs/DESIGN.md §20): dispatches_total
    # registered-and-zero is how a scrape PROVES sampling is off (the
    # free off-path), compile_events absent would let a recompile storm
    # burn the fleet with nothing to alert on, and the HBM watermark
    # vanishing reads as "pools never grew" — exactly the OOM-postmortem
    # blindness the ledger exists to end
    "dwt_profile_dispatch_seconds",
    "dwt_profile_samples_total",
    "dwt_profile_dispatches_total",
    "dwt_profile_achieved_bytes_per_second",
    "dwt_profile_roofline_ratio",
    "dwt_compile_events_total",
    "dwt_compile_seconds_total",
    "dwt_compile_cache_entries",
    "dwt_compile_variant_budget_entries",
    "dwt_hbm_owner_bytes",
    "dwt_hbm_watermark_bytes",
    # zero-loss streams (docs/DESIGN.md §23): attempts/succeeded
    # diverging is the failed-failover signal, resumed_requests
    # registered-and-zero is how a scrape PROVES no stream needed a
    # survivor, and the diverged counter absent would let a journal the
    # survivor cannot reproduce fail invisibly — the one failure mode
    # the verify queue exists to make loud
    "dwt_gateway_resume_attempts_total",
    "dwt_gateway_resume_succeeded_total",
    "dwt_gateway_resume_exhausted_requests_total",
    "dwt_gateway_replica_failures_total",
    "dwt_batching_resumed_requests_total",
    "dwt_batching_resume_diverged_requests_total",
    "dwt_slo_resume_pause_seconds",
    "dwt_slo_resumed_requests_total",
}


def check_registry(registry) -> List[str]:
    """Return a list of human-readable violations (empty = clean)."""
    problems: List[str] = []
    for m in registry.collect():
        name = m.name
        if not getattr(m, "help", "").strip():
            problems.append(f"{name}: missing help text")
        parts = name.split("_")
        if parts[0] != "dwt" or len(parts) < 3:
            problems.append(
                f"{name}: must be dwt_<subsystem>_<name>_<unit>")
            continue
        if parts[1] not in SUBSYSTEMS:
            problems.append(
                f"{name}: unknown subsystem {parts[1]!r} (known: "
                f"{sorted(SUBSYSTEMS)})")
        is_counter = getattr(m, "type", "") == "counter"
        stripped = parts[:-1] if parts[-1] == "total" else parts
        if is_counter and parts[-1] != "total":
            problems.append(f"{name}: counters must end in _total")
        if not is_counter and parts[-1] == "total":
            problems.append(
                f"{name}: _total is reserved for counters "
                f"(type is {m.type!r})")
        # unit may be one or two tokens (bytes_per_second)
        unit1 = stripped[-1]
        unit3 = "_".join(stripped[-3:]) if len(stripped) >= 3 else ""
        if (unit1 not in UNITS and unit3 not in UNITS
                and name not in UNIT_SUFFIX_EXEMPT):
            problems.append(
                f"{name}: missing unit suffix (allowed: {sorted(UNITS)})")
        # label-set lint: vocabulary, pinned sets, federation reserve
        labels = tuple(getattr(m, "label_names", ()) or ())
        for lab in labels:
            if lab not in KNOWN_LABELS:
                problems.append(
                    f"{name}: unknown label {lab!r} (known: "
                    f"{sorted(KNOWN_LABELS)})")
        want = REQUIRED_LABELS.get(name)
        if want is not None and tuple(sorted(labels)) != tuple(
                sorted(want)):
            problems.append(
                f"{name}: label set {sorted(labels)} must be exactly "
                f"{sorted(want)}")
        if (parts[1] != "gateway"
                and FEDERATION_RESERVED_LABELS & set(labels)):
            problems.append(
                f"{name}: label(s) "
                f"{sorted(FEDERATION_RESERVED_LABELS & set(labels))} are "
                "reserved for the /metrics/fleet relabeler (replica-side "
                "series must not pre-declare them)")
    return problems


# series that must NOT exist: the dwt_batching_prefix_* aliases were
# deprecated in PR 3 ("one release") and removed three releases later —
# re-registering one would resurrect a name dashboards already migrated
# off, so absence is linted like presence (docs/DESIGN.md §10 runbook)
FORBIDDEN_SERIES = {
    "dwt_batching_prefix_cache_hits_total",
    "dwt_batching_prefix_cache_misses_total",
    "dwt_batching_prefix_reused_tokens_total",
}


def check_required(registry) -> List[str]:
    """Presence lint for the standard catalog (run against the DEFAULT
    registry only — synthetic test registries legitimately hold other
    series sets)."""
    present = {m.name for m in registry.collect()}
    return ([f"required series {name} is not registered"
             for name in sorted(REQUIRED_SERIES - present)]
            + [f"removed series {name} is registered again (the "
               "deprecated alias was deleted; see FORBIDDEN_SERIES)"
               for name in sorted(FORBIDDEN_SERIES & present)])


def main() -> int:
    # repo root on sys.path when run as a script from anywhere
    import pathlib
    root = str(pathlib.Path(__file__).resolve().parents[1])
    if root not in sys.path:
        sys.path.insert(0, root)
    from distributed_inference_demo_tpu.telemetry import catalog  # noqa: F401
    from distributed_inference_demo_tpu.telemetry.metrics import REGISTRY

    problems = check_registry(REGISTRY) + check_required(REGISTRY)
    for p in problems:
        print(f"METRIC LINT: {p}", file=sys.stderr)
    if problems:
        print(f"{len(problems)} metric naming violation(s)",
              file=sys.stderr)
        return 1
    n = len(REGISTRY.collect())
    print(f"metric names OK ({n} series checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
