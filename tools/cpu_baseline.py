"""Measure the 2-node CPU baseline (BASELINE.json config #1 at true scale).

TinyLlama-1.1B split into 2 layer ranges across 2 localhost OS processes —
the reference's 2-device demo shape (``server.py:26-27``) with the header in
this process and stage 1 in a spawned worker, ZMQ sockets in between.  The
result is the denominator of bench.py's ``vs_baseline`` (north star:
TPU >= 10x this number).

Writes ``tools/cpu_baseline.json``; run on the bench host:

    python tools/cpu_baseline.py            # full TinyLlama-1.1B (~minutes)
    BENCH_MODEL=llama-test python tools/cpu_baseline.py   # smoke

Weights are random (seed-derived in both processes) — throughput does not
depend on weight values.  fp32 is used on CPU (its native dtype; bf16 is
emulated and slower there, and a handicapped baseline would overstate
``vs_baseline``).
"""

import json
import os
import platform
import subprocess
import sys
import time
from pathlib import Path

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ["PALLAS_AXON_POOL_IPS"] = ""

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

OUT_PATH = Path(__file__).resolve().parent / "cpu_baseline.json"


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from distributed_inference_demo_tpu.comm.transport import ZmqTransport
    from distributed_inference_demo_tpu.models import get_model_config
    from distributed_inference_demo_tpu.models.base import (
        slice_stage, split_layer_ranges)
    from distributed_inference_demo_tpu.models.decoder import init_full_params
    from distributed_inference_demo_tpu.ops.sampling import SamplingParams
    from distributed_inference_demo_tpu.runtime.distributed import (
        PipelineHeader, StageRuntime)

    model = os.environ.get("BENCH_MODEL", "tinyllama-1.1b")
    batch = int(os.environ.get("BENCH_BATCH", "8"))
    prompt_len = int(os.environ.get("BENCH_PROMPT", "64"))
    new_tokens = int(os.environ.get("BENCH_NEW_TOKENS", "32"))
    max_seq = prompt_len + new_tokens

    cfg = get_model_config(model).replace(dtype_name="float32")
    specs = split_layer_ranges(cfg.num_layers, 2)
    sampling = SamplingParams(temperature=0.7, top_k=7)  # reference defaults

    print(f"[cpu_baseline] {model} fp32, batch={batch}, "
          f"prompt={prompt_len}, new={new_tokens}, split="
          f"{[(s.layer_start, s.layer_end) for s in specs]}", file=sys.stderr)

    header_transport = ZmqTransport("header")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               BENCH_DTYPE="float32")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_inference_demo_tpu.runtime.worker_main",
         "--model", model, "--stage-id", "1", "--num-stages", "2",
         "--layer-start", str(specs[1].layer_start),
         "--layer-end", str(specs[1].layer_end),
         "--device-id", "w1", "--port", "0",
         "--header", f"header@{header_transport.address}",
         "--max-seq", str(max_seq), "--dtype", "float32",
         "--temperature", "0.7", "--top-k", "7",
         # generous: the header's own init/compile can take minutes on a
         # small CPU host, and the worker must not idle out meanwhile
         "--step-timeout", "1800"],
        stdout=subprocess.PIPE, stderr=sys.stderr, env=env,
        text=True, cwd=str(REPO))
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("WORKER_READY w1 "), line
        header_transport.connect("w1", line.split()[-1])

        print("[cpu_baseline] initializing header stage...", file=sys.stderr)
        full = init_full_params(jax.random.PRNGKey(0), cfg)
        header = PipelineHeader(
            StageRuntime(cfg, specs[0], slice_stage(full, cfg, specs[0]),
                         max_seq, sampling),
            header_transport, next_id="w1", step_timeout=600)

        prompt = (np.arange(batch * prompt_len, dtype=np.int64)
                  .reshape(batch, prompt_len) % 1000).astype(np.int32)

        print("[cpu_baseline] warmup (compiles both stages)...",
              file=sys.stderr)
        header.generate(prompt, 4)
        header.reset_stats()

        print("[cpu_baseline] timed run...", file=sys.stderr)
        t0 = time.perf_counter()
        toks = header.generate(prompt, new_tokens)
        dt = time.perf_counter() - t0
        assert toks.shape == (batch, new_tokens)
        tps = batch * new_tokens / dt

        stage_stats = header.collect_stats(num_stages=2, timeout=30)
        header.shutdown_pipeline()
        proc.wait(timeout=60)
    finally:
        if proc.poll() is None:
            proc.kill()
        header_transport.close()

    result = {
        "tokens_per_sec": round(tps, 3),
        "seconds": round(dt, 3),
        "model": model,
        "dtype": "float32",
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "num_stages": 2,
        "transport": "zmq tcp localhost",
        "host": platform.node(),
        "cpu": platform.processor() or platform.machine(),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "stage_stats": stage_stats,
    }
    OUT_PATH.write_text(json.dumps(result, indent=2) + "\n")
    print(f"[cpu_baseline] {tps:.2f} tok/s -> {OUT_PATH}", file=sys.stderr)
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
